"""Fused in-jit gradient aggregation + ``DistributedOptimizer``.

Reference analog: ``byteps/torch/__init__.py`` ``DistributedOptimizer``
(wraps the user's optimizer, intercepts gradients, push_pulls them, then
steps). The TPU-idiomatic form is an ``optax.GradientTransformation``
wrapper whose ``update`` runs **inside the user's shard_map/pmap'd train
step**: gradients are flattened, concatenated, partitioned into
``BYTEPS_PARTITION_BYTES`` chunks (declaration = pytree order, so chunk
issue order preserves the reference's priority semantics), and each chunk is
aggregated with a psum or the compressed collective. Error-feedback and
Nesterov-momentum state live in the optimizer state pytree (per-device,
sharded over dp — each device is a "worker" with its own residual), which is
the pure-functional replacement for the reference's C++ side buffers.
"""

from __future__ import annotations

import os
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from byteps_tpu.common.config import get_config
from byteps_tpu.comm.ici import (
    compressed_allreduce_local,
    compressed_reduce_scatter_local,
)
from byteps_tpu.compression import from_params
from byteps_tpu.compression.error_feedback import CompressionSpec, momentum_step


def _flatten_concat(tree):
    leaves = jax.tree.leaves(tree)
    flats = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
    sizes = [f.shape[0] for f in flats]
    return jnp.concatenate(flats) if len(flats) > 1 else flats[0], sizes


def _unconcat_unflatten(flat, tree, sizes):
    leaves, treedef = jax.tree.flatten(tree)
    outs = []
    off = 0
    for leaf, s in zip(leaves, sizes):
        outs.append(flat[off:off + s].reshape(leaf.shape).astype(leaf.dtype))
        off += s
    return jax.tree.unflatten(treedef, outs)


def _chunk_bounds(total: int, chunk_elems: int):
    bounds = []
    off = 0
    while off < total:
        ln = min(chunk_elems, total - off)
        bounds.append((off, ln))
        off += ln
    return bounds or [(0, total)]


def _aggregate_flat(
    flat: jnp.ndarray,
    axis: str,
    n: int,
    average: bool,
    spec: CompressionSpec,
    rng: Optional[jnp.ndarray],
    ef_flat: Optional[jnp.ndarray],
    chunk_elems: int,
    two_way: bool,
    chunk_id_offset: int = 0,
):
    """Chunk a flat fp32 grad vector and aggregate each chunk over ``axis``.

    Returns ``(agg_flat, new_ef_flat_or_None, num_chunks)``. The chunking is
    the reference's tensor partitioning (BYTEPS_PARTITION_BYTES,
    operations.cc); under jit the chunk collectives are issued in order and
    XLA overlaps them with surrounding compute.
    """
    total = flat.shape[0]
    bounds = _chunk_bounds(total, chunk_elems)
    if spec.enabled and rng is None:
        if spec.compressor.stochastic:
            raise ValueError(
                f"{spec.compressor.name} requires an rng that advances "
                "every step; pass rng= (DistributedOptimizer does this "
                "automatically from its step count)"
            )
        rng = jax.random.PRNGKey(0)

    out_chunks = []
    new_e_chunks = [] if ef_flat is not None else None

    def one_chunk(g, crng, e):
        """Per-chunk body, shared by the batched (vmapped) full chunks
        and the ragged tail — one definition so their semantics cannot
        diverge."""
        res = compressed_allreduce_local(
            g, crng, spec.compressor, axis, n,
            average=average, two_way=two_way, ef_residual=e,
        )
        return res if e is not None else (res, None)

    # BYTEPS_COMPRESS_BATCH_CHUNKS > 1 runs full chunks in vmapped
    # groups of that size (an UNROLLED loop of vmap calls — see the
    # scan note below): per-chunk semantics are unchanged (same fold_in
    # key per chunk id, selection/EF still per chunk_elems partition —
    # the wire contract), but each group's codec runs as
    # (group, chunk_elems) array ops instead of per-chunk sequential
    # op-chains, and the group size bounds the live f32 intermediates
    # (an all-chunks vmap OOMs a v5e next to the model+opt state).
    # Remainder full chunks take one smaller vmap; the ragged tail
    # keeps the scalar path (its k resolves against the true tail
    # length, exactly as before). Default 1 = OFF, and deliberately so:
    # with the fused n==1 roundtrip and the Pallas codec kernels each
    # per-chunk body is already a few big ops, and vmap batching only
    # adds slicing/stacking glue — measured on v5e, gpt2m+topk-block
    # 80.4 ms (off) vs 92.2 ms (groups of 16) and bert+onebit 43.3 vs
    # 68.4. Raise it only for codecs that still emit many small XLA ops
    # per chunk, and re-measure (docs/env.md).
    group = int(os.environ.get("BYTEPS_COMPRESS_BATCH_CHUNKS", "1"))
    nfull = total // chunk_elems
    pre_added = False
    if spec.enabled and nfull > 1 and group > 1:
        # The EF add IS hoisted to ONE whole-flat pass here (the tail
        # chunks below then slice the pre-added flat and ask only for
        # the residual back — compressed_allreduce_local's documented
        # return_residual contract), and the chunk views are chosen so
        # every reshape is a layout no-op: a 1-D f32 array tiles as
        # 1024 consecutive elements, and any (..., m, 128) view with
        # m % 8 == 0 preserves that physical order — whereas the naive
        # (nchunks, chunk_elems) 2-D stacking interleaves 8 CHUNKS per
        # tile and forced a full relayout of the gradient in each
        # direction (round-5 xprof: ~22 ms/step of 'data formatting' on
        # GPT-2-medium, on top of per-chunk small-op overhead the
        # batching already removes).
        lanes = 128 if chunk_elems % 128 == 0 else 1
        m = chunk_elems // lanes
        want_res = ef_flat is not None
        if want_res:
            flat = flat + ef_flat          # the single whole-flat EF add
            pre_added = True

        def body(g, k):
            r = compressed_allreduce_local(
                g.reshape(-1), k, spec.compressor, axis, n,
                average=average, two_way=two_way,
                return_residual=want_res,
            )
            return r if want_res else (r, jnp.zeros((), jnp.float32))

        def vchunk(gs, ids):
            keys = jax.vmap(
                lambda i: jax.random.fold_in(rng, chunk_id_offset + i)
            )(ids)
            return jax.vmap(body)(gs, keys)

        # unrolled loop of vmapped groups — NOT a lax.scan: scan stacks
        # its per-iteration outputs with full-array dynamic-update-slice
        # copies every step (measured 2.5× WORSE than the sequential
        # per-chunk form), while the unrolled concatenate lets XLA
        # write each group's output once. The (·, m, lanes) group view
        # keeps the minor dims layout-compatible with the flat source.
        for g0 in range(0, nfull, group):
            g1 = min(nfull, g0 + group)
            gs = jax.lax.slice_in_dim(
                flat, g0 * chunk_elems,
                g1 * chunk_elems).reshape(g1 - g0, m, lanes)
            out_g, ne_g = vchunk(gs, jnp.arange(g0, g1))
            out_chunks.append(out_g.reshape(-1))
            if ef_flat is not None:
                new_e_chunks.append(ne_g.reshape(-1))
        bounds = bounds[nfull:]
        ci0 = nfull
    else:
        ci0 = 0

    for ci, (off, ln) in enumerate(bounds, start=ci0):
        g = jax.lax.slice_in_dim(flat, off, off + ln)
        if spec.enabled:
            crng = jax.random.fold_in(rng, chunk_id_offset + ci)
            if pre_added:
                # flat already carries the residual (hoisted add above)
                out, ne = compressed_allreduce_local(
                    g, crng, spec.compressor, axis, n,
                    average=average, two_way=two_way,
                    return_residual=True,
                )
                new_e_chunks.append(ne)
            else:
                e = (
                    jax.lax.slice_in_dim(ef_flat, off, off + ln)
                    if ef_flat is not None
                    else None
                )
                out, ne = one_chunk(g, crng, e)
                if e is not None:
                    new_e_chunks.append(ne)
        else:
            s = jax.lax.psum(g, axis)
            out = s / n if average else s
            if new_e_chunks is not None:
                # residual contract is fp32 regardless of the aggregation
                # dtype (g may be bf16 under BYTEPS_REDUCE_DTYPE)
                new_e_chunks.append(jnp.zeros(g.shape, jnp.float32))
        out_chunks.append(out)
    agg = out_chunks[0] if len(out_chunks) == 1 else jnp.concatenate(out_chunks)
    new_e = None
    if new_e_chunks is not None:
        new_e = (
            new_e_chunks[0] if len(new_e_chunks) == 1
            else jnp.concatenate(new_e_chunks)
        )
    return agg, new_e, len(bounds) + ci0


def _vma_groups(leaves):
    """Group leaf indices by their VMA (varying-mesh-axes) type.

    Concatenating a tp-sharded leaf with a replicated one would widen the
    replicated leaf's inferred variance to the union and break shard_map's
    out_specs check (and hide real type information). Grouping keeps each
    concat type-pure; without VMA tracking every leaf lands in one group,
    which is exactly the old behavior.
    """
    groups: Dict[frozenset, list] = {}
    for i, l in enumerate(leaves):
        key = frozenset(getattr(jax.typeof(l), "vma", ()) or ())
        groups.setdefault(key, []).append(i)
    return list(groups.values())


def push_pull_inside(
    grads,
    axis: Optional[str] = None,
    n: Optional[int] = None,
    average: bool = True,
    spec: Optional[CompressionSpec] = None,
    rng: Optional[jnp.ndarray] = None,
    ef_residual: Optional[jnp.ndarray] = None,
    partition_bytes: Optional[int] = None,
    two_way: bool = True,
):
    """Aggregate a gradient pytree across the dp axis, **inside** shard_map.

    Returns ``agg_grads`` (same structure as ``grads``), or
    ``(agg_grads, new_ef_residual)`` when ``ef_residual`` is given (a flat
    fp32 vector of the total parameter count, laid out in VMA-group order —
    treat it as opaque state).

    This is the fused analog of per-tensor ``push_pull`` calls: one trace,
    chunked collectives in declaration order, XLA overlaps them.
    """
    cfg = get_config()
    axis = axis or cfg.dp_axis
    if n is None:
        n = jax.lax.axis_size(axis)
    if spec is None:
        spec = from_params(None)
    if n == 1 and not spec.enabled:
        # single-worker fast path: aggregation is the identity — skip the
        # flatten/chunk machinery entirely (reference: single-machine mode
        # short-circuits the PS pipeline, operations.cc queue-list build).
        # Residual is zeroed exactly like the chunked uncompressed path: no
        # compression happened, so no error may be carried forward.
        if ef_residual is not None:
            return grads, jnp.zeros_like(ef_residual)
        return grads
    partition_bytes = partition_bytes or cfg.partition_bytes
    # BYTEPS_REDUCE_DTYPE: the aggregation dtype for uncompressed psums —
    # bfloat16 halves TOTAL ICI bytes (chunks still carry partition_bytes
    # each, so half as many chunks) at reduced summation precision (the
    # reference PS always sums fp32; this is a TPU-only lever).
    # Compression requires fp32 (kernel contract), and the EF residual
    # stays fp32 either way.
    acc_dtype = jnp.dtype(
        "float32" if spec.enabled else cfg.reduce_dtype
    )
    chunk_elems = max(1, partition_bytes // acc_dtype.itemsize)

    leaves, treedef = jax.tree.flatten(grads)
    out_leaves = [None] * len(leaves)
    groups = _vma_groups(leaves)
    ef_off = 0
    chunk_id = 0
    new_e_parts = [] if ef_residual is not None else None
    for idxs in groups:
        flats = [jnp.ravel(leaves[i]).astype(acc_dtype) for i in idxs]
        sizes = [f.shape[0] for f in flats]
        flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
        gtotal = flat.shape[0]
        e = (
            jax.lax.slice_in_dim(ef_residual, ef_off, ef_off + gtotal)
            if ef_residual is not None else None
        )
        agg, new_e, nchunks = _aggregate_flat(
            flat, axis, n, average, spec, rng, e, chunk_elems, two_way,
            chunk_id_offset=chunk_id,
        )
        chunk_id += nchunks
        if new_e_parts is not None:
            new_e_parts.append(new_e)  # always set when ef_residual given
        off = 0
        for i, s in zip(idxs, sizes):
            leaf = leaves[i]
            out_leaves[i] = (
                agg[off:off + s].reshape(leaf.shape).astype(leaf.dtype)
            )
            off += s
        ef_off += gtotal
    agg_tree = jax.tree.unflatten(treedef, out_leaves)
    if ef_residual is not None:
        new_e_flat = (
            new_e_parts[0] if len(new_e_parts) == 1
            else jnp.concatenate(new_e_parts)
        )
        return agg_tree, new_e_flat
    return agg_tree


class DistributedOptState(NamedTuple):
    inner: Any
    count: jnp.ndarray                      # step counter (rng derivation)
    ef: Optional[jnp.ndarray]               # flat EF residual or None
    momentum: Optional[jnp.ndarray]         # flat momentum buffer or None


def DistributedOptimizer(
    tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    axis: Optional[str] = None,
    num_devices: Optional[int] = None,
    average: bool = True,
    partition_bytes: Optional[int] = None,
    seed: int = 0,
    per_device_numel: Optional[int] = None,
    state_leading: tuple = (),
    zero: bool = False,
    dcn_axis: Optional[str] = None,
    num_dcn: Optional[int] = None,
) -> optax.GradientTransformation:
    """Wrap an optax transformation with BytePS gradient aggregation.

    ``update`` MUST be called inside a shard_map/pmap context that defines
    the dp ``axis``. Gradients entering ``update`` are per-device; the
    wrapper aggregates them (compressed if configured), updates EF/momentum
    state, then applies the inner transformation to the aggregated grads.

    ``zero=True`` is ZeRO-1 (no reference analog — the reference keeps
    full optimizer replicas per GPU worker): the inner state lives on one
    flat fp32 vector sharded over dp, gradients arrive at each worker as
    its owned segment (``psum_scatter``, or the compressed collective's
    owner-sum half), the inner ``tx`` steps only that segment, and the
    resulting *updates* segment is all_gathered — optimizer-state HBM
    drops to 1/n_dp, and the second wire direction carries update bytes
    instead of gradient bytes. Requires the check_vma=False step mode
    (the all_gathered updates are replicated but typed dp-varying). The
    flat gradient aggregates as ONE scatter — ``partition_bytes``
    chunking does not apply (chunk boundaries would bake into the inner
    state layout, breaking the tuner's retrace-without-reinit contract).

    ZeRO restriction: the inner ``tx`` must be ELEMENTWISE in the
    gradient (sgd / momentum / adam / adamw / scale chains) — it sees
    only this worker's 1/n segment, so cross-element transforms compute
    from partial data (clip_by_global_norm would clip by the segment
    norm; adafactor's factoring collapses on the flat 1-D layout). Use
    ``zero=False`` for those.

    When the step composes other model-parallel axes (pp stages, ep expert
    groups) each device's gradient pytree is a *shard* of the params:
    pass ``per_device_numel`` (that shard's element count) and
    ``state_leading`` (the sizes of those axes, e.g. ``(n_pp,)``) so the
    EF/momentum worker buffers come out shaped
    ``state_leading + (n_dp * per_device_numel,)`` — shard them
    ``P(pp_axis, ..., dp_axis)`` and every device sees exactly its own
    flat residual (``update`` ravels whatever block arrives).

    ``dcn_axis`` turns on the HIERARCHICAL multi-slice path (the BytePS
    thesis applied to an ICI×DCN topology): each slice reduce-scatters
    its gradients RAW over the fast intra-slice ``axis`` (every dp rank
    owns one flat segment), the owned segment is exchanged across slices
    over ``dcn_axis`` — compressed with EF when ``compression_params``
    is set, so the codec pays down only the slow inter-slice wire — and
    the result all_gathers back over ``axis``. EF/momentum residuals are
    per-(slice, dp-rank) SEGMENT state: buffers come out sized
    ``ceil(total/n_dp)`` per device, sharded ``P(..., (dcn_axis, axis))``
    via ``dp_state_specs(dcn_axis=)``. Incompatible with ``zero`` (the
    ZeRO-1 segment flow owns the scatter already). On a slice-only mesh
    pass the DCN axis as ``axis`` instead — the legacy single-axis path
    then compresses straight over DCN.

    Reference: ``DistributedOptimizer(optimizer, named_parameters,
    compression, ...)`` in byteps/torch — same contract, functional form.
    """
    cfg = get_config()
    axis_name = axis or cfg.dp_axis
    spec = from_params(compression_params)
    if zero and dcn_axis is not None:
        raise ValueError(
            "zero=True and dcn_axis are mutually exclusive — ZeRO-1's "
            "segment flow already owns the reduce-scatter; shard over "
            "one axis or use the ZeRO-3 factory for multi-slice FSDP")
    n_dcn = (num_dcn if num_dcn is not None else 1) if dcn_axis else 1

    def _seg_of(total: int, n: int) -> int:
        return -(-total // n)

    def init_fn(params):
        # count elements from shapes — params may be tp-sharded global
        # arrays here (no ravel/concat, which would force a resharding)
        total = per_device_numel if per_device_numel is not None else sum(
            int(np.prod(l.shape)) if l.ndim else 1
            for l in jax.tree.leaves(params)
        )
        if zero:
            n = num_devices if num_devices is not None else len(jax.devices())
            seg = -(-total // n)
            proto = jnp.zeros(tuple(state_leading) + (n * seg,), jnp.float32)
            inner = tx.init(proto)
        else:
            inner = tx.init(params)
        # EF / momentum are PER-DEVICE worker state (each device is one
        # reference worker): globally state_leading + (n * total,), sharded
        # over (those axes..., dp) so each device's shard_map block is its
        # own (total,) buffer. Shard with `dp_state_specs()`; see that
        # helper's docstring. Under dcn_axis each worker's residual covers
        # only its OWNED dp segment (the only data it compresses), so the
        # global buffer is (n_dcn * n_dp * seg,) over (dcn, dp).
        n = num_devices if num_devices is not None else len(jax.devices())
        if dcn_axis is not None:
            shape = tuple(state_leading) + (n_dcn * n * _seg_of(total, n),)
        else:
            shape = tuple(state_leading) + (n * total,)
        ef = (
            jnp.zeros(shape, jnp.float32)
            if (spec.enabled and spec.ef)
            else None
        )
        mom = (
            jnp.zeros(shape, jnp.float32)
            if (spec.enabled and spec.momentum)
            else None
        )
        return DistributedOptState(
            inner=inner, count=jnp.zeros((), jnp.int32), ef=ef, momentum=mom
        )

    def _zero_update(grads, state, params, n, rng, ef_shape, mom_shape):
        """ZeRO-1 step: segment-owner aggregation → inner tx on the owned
        segment → all_gather of the updates segment."""
        if params is None:
            raise ValueError(
                "ZeRO mode requires params= in update (the inner transform "
                "steps a params segment)")
        flat, sizes = _flatten_concat(grads)
        total = flat.shape[0]
        seg = -(-total // n)
        mom = state.momentum
        if spec.enabled and mom is not None:
            flat, mom = momentum_step(flat, mom, spec.mu)
        if spec.enabled:
            if state.ef is not None:
                my_seg, new_ef = compressed_reduce_scatter_local(
                    flat, rng, spec.compressor, axis_name, n,
                    average=average, ef_residual=state.ef)
            else:
                my_seg = compressed_reduce_scatter_local(
                    flat, rng, spec.compressor, axis_name, n,
                    average=average)
                new_ef = None
        else:
            # BYTEPS_REDUCE_DTYPE applies here as on the chunked path:
            # bf16 halves the scatter's wire bytes, sum accuracy reduced
            padded = jnp.pad(flat, (0, n * seg - total)).astype(
                jnp.dtype(cfg.reduce_dtype))
            s = jax.lax.psum_scatter(
                padded, axis_name, scatter_dimension=0,
                tiled=True).astype(jnp.float32)
            my_seg = s / n if average else s
            new_ef = state.ef
        if cfg.trace_on and _host_callbacks_supported():
            jax.debug.callback(
                _fused_trace_callback, state.count,
                total_elems=total, chunks=1,
            )
        # the inner state block arrives (1, ..., 1, seg) under its
        # (pp/ep..., dp) sharding — flatten for the segment step, restore
        # the block shape on the way out
        lead = len(state_leading)

        def to_seg(l):
            if (hasattr(l, "ndim") and l.ndim == lead + 1
                    and l.shape[-1] == seg):
                return l.reshape(seg)
            return l

        inner_seg = jax.tree.map(to_seg, state.inner)
        p_flat, _ = _flatten_concat(params)
        p_pad = jnp.pad(p_flat, (0, n * seg - total))
        my_id = jax.lax.axis_index(axis_name)
        p_seg = jax.lax.dynamic_slice_in_dim(p_pad, my_id * seg, seg)
        upd_seg, new_inner_seg = tx.update(my_seg, inner_seg, p_seg)
        upd_full = jax.lax.all_gather(upd_seg, axis_name, axis=0, tiled=True)
        updates = _unconcat_unflatten(upd_full[:total], grads, sizes)
        new_inner = jax.tree.map(
            lambda nl, ol: nl.reshape(ol.shape)
            if (hasattr(ol, "shape") and hasattr(nl, "shape")
                and nl.shape != ol.shape) else nl,
            new_inner_seg, state.inner)
        if new_ef is not None:
            new_ef = new_ef.reshape(ef_shape)
        if mom is not None:
            mom = mom.reshape(mom_shape)
        return updates, DistributedOptState(
            inner=new_inner, count=state.count + 1, ef=new_ef, momentum=mom
        )

    def _hier_update(grads, state, params, n, rng, ef_shape, mom_shape,
                     chunk_elems):
        """Multi-slice step: raw ICI reduce-scatter over dp → compressed
        (EF'd, chunked) exchange of the owned segment across dcn_axis →
        raw ICI all_gather — only segment-sized compressed payloads ever
        cross the DCN wire, and each does so exactly once."""
        flat, sizes = _flatten_concat(grads)
        total = flat.shape[0]
        seg = _seg_of(total, n)
        if n > 1:
            padded = jnp.pad(flat, (0, n * seg - total))
            my_seg = jax.lax.psum_scatter(
                padded, axis_name, scatter_dimension=0, tiled=True)
        else:
            my_seg = flat
        mom = state.momentum
        if mom is not None:
            my_seg, mom = momentum_step(my_seg, mom, spec.mu)
        agg_seg, new_ef, nchunks = _aggregate_flat(
            my_seg, dcn_axis, n_dcn, False, spec, rng, state.ef,
            chunk_elems, spec.two_way,
        )
        if n > 1:
            full = jax.lax.all_gather(
                agg_seg, axis_name, axis=0, tiled=True)[:total]
        else:
            full = agg_seg[:total]
        if average:
            full = full / (n * n_dcn)
        updates_grads = _unconcat_unflatten(full, grads, sizes)
        if cfg.trace_on and _host_callbacks_supported():
            jax.debug.callback(
                _fused_trace_callback, state.count,
                total_elems=total, chunks=nchunks,
            )
        updates, new_inner = tx.update(updates_grads, state.inner, params)
        if new_ef is not None:
            new_ef = new_ef.reshape(ef_shape)
        if mom is not None:
            mom = mom.reshape(mom_shape)
        return updates, DistributedOptState(
            inner=new_inner, count=state.count + 1, ef=new_ef, momentum=mom
        )

    def update_fn(grads, state: DistributedOptState, params=None):
        n = num_devices if num_devices is not None else jax.lax.axis_size(axis_name)
        # spec.seed (reference compression_params 'seed') co-determines the
        # stream so configs differing in seed actually differ
        rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), spec.seed), state.count
        )

        total = sum(
            int(np.prod(l.shape)) if l.ndim else 1
            for l in jax.tree.leaves(grads)
        )
        # inside shard_map the state block may carry collapsed leading axes
        # ((1, ..., total) under a (pp, ..., dp) sharding) — work on the
        # flat view and restore the block shape on return
        ef_shape = state.ef.shape if state.ef is not None else None
        mom_shape = state.momentum.shape if state.momentum is not None else None
        state = state._replace(
            ef=state.ef.ravel() if state.ef is not None else None,
            momentum=(state.momentum.ravel()
                      if state.momentum is not None else None),
        )
        expected = _seg_of(total, n) if dcn_axis is not None else total
        for buf, kind in ((state.ef, "EF"), (state.momentum, "momentum")):
            if buf is not None and buf.shape[0] != expected:
                raise ValueError(
                    f"{kind} state has {buf.shape[0]} elements per device but "
                    f"this device expects {expected}. Most likely "
                    "DistributedOptimizer was built without num_devices= on a "
                    "mesh whose dp axis does not span all jax.devices() — "
                    "pass num_devices=mesh.shape['dp'] (and per_device_numel= "
                    "on pp/ep meshes where each device grads a param shard)."
                )

        if zero:
            return _zero_update(grads, state, params, n, rng,
                                ef_shape, mom_shape)

        if dcn_axis is not None and spec.enabled:
            pb = partition_bytes or cfg.partition_bytes
            return _hier_update(grads, state, params, n, rng,
                                ef_shape, mom_shape, max(1, pb // 4))

        # raw multi-slice: one psum over the combined (dcn, dp) tuple axis
        # — VMA-compatible, XLA lowers it hierarchically on hybrid meshes
        agg_axis = (dcn_axis, axis_name) if dcn_axis is not None else axis_name
        agg_n = n * n_dcn

        mom = state.momentum
        if spec.enabled and mom is not None:
            # Nesterov momentum before compression (reference:
            # nesterov_momentum.cc decorator)
            flat, sizes = _flatten_concat(grads)
            flat, mom = momentum_step(flat, mom, spec.mu)
            grads_in = _unconcat_unflatten(flat, grads, sizes)
        else:
            grads_in = grads

        if spec.enabled and state.ef is not None:
            agg, new_ef = push_pull_inside(
                grads_in, agg_axis, agg_n, average, spec, rng,
                ef_residual=state.ef, partition_bytes=partition_bytes,
                two_way=spec.two_way,
            )
        else:
            agg = push_pull_inside(
                grads_in, agg_axis, agg_n, average, spec, rng,
                partition_bytes=partition_bytes, two_way=spec.two_way,
            )
            new_ef = state.ef

        if cfg.trace_on and _host_callbacks_supported():
            # Per-execution dispatch-site marker (SURVEY §5.1): the fused
            # path lives inside XLA where the host tracer cannot see, so a
            # debug callback surfaces one event per executed step and
            # advances the trace step window. count makes it idempotent
            # across shard_map's per-shard duplicates; zero overhead when
            # BYTEPS_TRACE_ON is off (branch is trace-time static).
            pb = partition_bytes or cfg.partition_bytes
            itemsize = (
                4 if spec.enabled else jnp.dtype(cfg.reduce_dtype).itemsize
            )
            nchunks = -(-total * itemsize // pb)
            jax.debug.callback(
                _fused_trace_callback, state.count,
                total_elems=total, chunks=nchunks,
            )

        updates, new_inner = tx.update(agg, state.inner, params)
        if new_ef is not None:
            new_ef = new_ef.reshape(ef_shape)
        if mom is not None:
            mom = mom.reshape(mom_shape)
        return updates, DistributedOptState(
            inner=new_inner, count=state.count + 1, ef=new_ef, momentum=mom
        )

    return optax.GradientTransformation(init_fn, update_fn)


def _host_callbacks_supported() -> bool:
    """Some PJRT plugins (the axon TPU tunnel) reject host send/recv
    callbacks outright; tracing must degrade to eager-path events there
    instead of crashing every traced step. Backend names lie (the tunnel
    registers as "tpu" while its plugin refuses callbacks), so the only
    reliable test is a one-time probe: run a tiny jitted debug.callback
    and see whether the runtime accepts it. Probed once per process,
    only on tracing sessions (the caller gates on cfg.trace_on)."""
    cached = getattr(_host_callbacks_supported, "_cached", None)
    if cached is not None:
        return cached

    ok = True
    try:
        @jax.jit
        def _probe(x):
            jax.debug.callback(lambda _v: None, x)
            return x + 1

        def _run_probe():
            res = _probe(jnp.zeros(()))
            if not hasattr(res, "block_until_ready"):
                # the nested jit staged into an ambient trace we could
                # not escape (no eval_context on this jax): the probe is
                # INCONCLUSIVE — degrade SAFE (markers off for this
                # trace; an unproven callback baked into the step would
                # crash every step on a callback-rejecting backend) but
                # don't cache, so a later out-of-trace call can upgrade
                return False
            res.block_until_ready()
            return True

        # The caller is usually mid-trace (update_fn under the user's
        # jit): on jax versions where a nested jit call stages into the
        # ambient trace, the probe result would be a Tracer — probe
        # under eval_context so it always executes concretely.
        clean = getattr(jax.core, "trace_state_clean", lambda: True)()
        ectx = getattr(jax.core, "eval_context", None)
        if not clean and ectx is not None:
            with ectx():
                conclusive = _run_probe()
        else:
            conclusive = _run_probe()
        if not conclusive:
            return False
    except Exception as e:  # noqa: BLE001 — any refusal means unsupported
        ok = False
        from byteps_tpu.common.logging import get_logger

        get_logger("jax.optimizer").warning(
            "fused-path trace markers disabled: this backend rejects "
            "host callbacks (%s) — step advance falls back to the "
            "host-side wrapper/eager events", type(e).__name__,
        )
    _host_callbacks_supported._cached = ok  # type: ignore[attr-defined]
    return ok


def _fused_trace_callback(count, total_elems: int, chunks: int) -> None:
    from byteps_tpu.common.tracing import get_tracer

    get_tracer().fused_step(
        int(count), {"total_elems": int(total_elems), "chunks": int(chunks)}
    )


def dp_state_specs(axis: Optional[str] = None,
                   leading_axes: tuple = (),
                   dcn_axis: Optional[str] = None) -> DistributedOptState:
    """PartitionSpec prefix-tree for a ``DistributedOptState``.

    Use as the shard_map in/out spec for the optimizer state: the inner
    optax state and step count are replicated (every device applies the same
    aggregated update), while the EF/momentum buffers are sharded over the
    dp axis (per-device worker state)::

        spec = bps.dp_state_specs()
        step = jax.shard_map(per_device_step, mesh=mesh,
                             in_specs=(P(), spec, P("dp"), P("dp")),
                             out_specs=(P(), spec), check_vma=False)

    ``leading_axes`` names the extra state axes of a pp/ep-composed
    optimizer built with ``state_leading`` (buffer spec becomes
    ``P(*leading_axes, dp)``). ``dcn_axis`` names the slice axis of a
    hierarchical (``DistributedOptimizer(dcn_axis=...)``) optimizer —
    the segment buffers then shard over the combined ``(dcn, dp)`` axes.
    """
    from jax.sharding import PartitionSpec as P

    axis = axis or get_config().dp_axis
    if dcn_axis is not None:
        buf = P(*leading_axes, (dcn_axis, axis))
    else:
        buf = P(*leading_axes, axis)
    return DistributedOptState(inner=P(), count=P(), ef=buf, momentum=buf)
