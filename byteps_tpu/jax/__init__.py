"""byteps_tpu.jax — the JAX framework adapter.

Mirrors the reference's per-framework adapter surface
(``byteps/torch/__init__.py`` is the model: ``init``, ``rank``/``size``,
``push_pull``, ``DistributedOptimizer``, ``broadcast_parameters``), as the
BASELINE north star's ``byteps/jax/`` package. Typical use::

    import byteps_tpu.jax as bps

    bps.init()
    tx = bps.DistributedOptimizer(
        optax.sgd(0.1),
        compression_params={"compressor": "onebit", "ef": "vanilla"},
    )
    # inside a shard_map'd per-device train step:
    #   updates, opt_state = tx.update(grads, opt_state, params)

Two aggregation paths (SURVEY §7 phase 2/3):

* **fused** — ``DistributedOptimizer`` / ``push_pull_inside`` used inside the
  user's jitted ``shard_map`` step: gradients are flattened, chunked to
  ``BYTEPS_PARTITION_BYTES``, and each chunk aggregated with a psum or the
  compressed collective, all in one XLA program. This is the
  peak-bandwidth path — XLA's scheduler overlaps chunk collectives.
* **eager** — ``push_pull``/``push_pull_async`` on stacked ``(N, ...)``
  arrays outside jit: each tensor is declared (priority = -declaration
  order), partitioned, and its chunks dispatched through the credit-limited
  priority scheduler, preserving the reference's dynamic inter-tensor
  reordering and giving per-stage chrome traces.
"""

from __future__ import annotations

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()

import dataclasses
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from byteps_tpu.common.config import Config, get_config
from byteps_tpu.common.stage_orders import (
    EAGER_STAGE_ORDER,
    HYBRID_STAGE_ORDER,
)
from byteps_tpu.common.logging import bps_check, get_logger
from byteps_tpu.common.partition import OwnerTable, TensorRegistry
from byteps_tpu.common.scheduler import (
    Handle,
    PartitionTask,
    PipelineScheduler,
    Stage,
)
from byteps_tpu.common.tracing import get_tracer
from byteps_tpu.comm.ici import (
    all_gather_flat,
    allreduce_flat,
    broadcast_flat,
    compressed_allreduce_flat,
    compressed_reduce_scatter_flat,
    reduce_scatter_flat,
)
from byteps_tpu.comm.mesh import device_mesh
from byteps_tpu.compression import from_params
from byteps_tpu.compression.error_feedback import CompressionSpec, momentum_step

from byteps_tpu.jax.optimizer import (  # noqa: F401,E402
    DistributedOptimizer,
    DistributedOptState,
    dp_state_specs,
    push_pull_inside,
)
from byteps_tpu.jax.tuned_step import AutoTunedStep  # noqa: F401,E402

log = get_logger("jax")


class _BytePSJaxState:
    def __init__(self) -> None:
        self.initialized = False
        self.cfg: Optional[Config] = None
        self.mesh = None
        self.registry: Optional[TensorRegistry] = None
        self.scheduler: Optional[PipelineScheduler] = None
        self.spec: Optional[CompressionSpec] = None
        self.versions: Dict[str, int] = {}
        # per-(name, part_idx) EF residual / momentum buffers, (N, plen)
        self.ef_state: Dict[Any, jnp.ndarray] = {}
        self.mom_state: Dict[Any, jnp.ndarray] = {}
        self.base_rng = None
        self.anon_counter = 0
        self.lock = threading.Lock()
        # Serializes ICI collective DISPATCH across stage pool threads:
        # XLA launches collective programs in dispatch order per device,
        # so two host threads dispatching (reduce-scatter from REDUCE,
        # all-gather from ALLGATHER) concurrently can enqueue them in
        # different orders on different devices — a rendezvous deadlock
        # (observed on the CPU backend, same hazard on TPU). Dispatch is
        # async; only the enqueue order is pinned.
        self.ici_lock = threading.Lock()
        self.tuner = None
        self.psworker = None        # DCN tier client (distributed mode)
        # sharded-wire hierarchical mode: one PSWorker per pod controller
        # (psworker aliases psworkers[0]); owners maps partition keys to
        # the controller whose NIC carries them
        self.psworkers: List[Any] = []
        self.owners: Optional[OwnerTable] = None
        self.owner_failovers = 0
        # scale-up elasticity: hooks fired with the live pod count after
        # join() adopts a membership change (shard remap, LR rescale)
        self.membership_hooks: List[Any] = []
        # bumped (under lock) by _fail_owner's EF/momentum reset; a
        # COMPRESS that read its state before the bump must not write the
        # stale residual back after it (see _compress_stage)
        self.failover_gen = 0
        self.inited_keys = set()   # {(owner, key)} successfully init'ed


_state = _BytePSJaxState()


def init(
    mesh=None,
    compression_params: Optional[Dict[str, Any]] = None,
    seed: int = 0,
) -> None:
    """Initialize the adapter (reference: ``byteps_init`` / ``BytePSGlobal::Init``).

    On multi-host TPU pods with ``BYTEPS_JAX_DISTRIBUTED=1`` this joins the
    global ``jax.distributed`` group (the launcher's ``_jd_boot`` already
    did, making this a no-op); ``mesh`` then spans all hosts' devices.
    """
    if _state.initialized:
        return
    cfg = get_config()
    from byteps_tpu.comm.distributed import maybe_init_distributed

    maybe_init_distributed(cfg)
    from byteps_tpu.comm.distributed import is_multiprocess

    if cfg.hybrid_sharded and is_multiprocess():
        # The sharded graph's COPYD2H/COPYH2D move per-device SEGMENTS of
        # the reduce-scattered array; in a multi-process global mesh those
        # segments span non-addressable devices and jax.device_get would
        # throw on every push_pull. The dataflow needs per-process
        # addressable-shard plumbing (future work) — until then the
        # classic graph (full allreduce, controller 0's NIC) is the
        # correct multi-process hybrid.
        log.warning(
            "BYTEPS_HYBRID_SHARDED is not yet supported in multi-process "
            "global-mesh mode; falling back to the unsharded hybrid graph")
        cfg = dataclasses.replace(cfg, hybrid_sharded=False)
    _state.cfg = cfg
    _state.mesh = mesh if mesh is not None else device_mesh()
    _state.registry = TensorRegistry()
    _state.spec = from_params(compression_params)
    _state.base_rng = jax.random.PRNGKey(seed)
    tracer = get_tracer()
    if cfg.is_distributed:
        # Hybrid two-tier pipeline (reference root-GPU queue list,
        # operations.cc GetPushQueueList: REDUCE → COPYD2H → COMPRESS →
        # PUSH → PULL → DECOMPRESS → COPYH2D; BROADCAST is implicit — the
        # H2D value is the replicated result). Intra-pod reduction rides
        # ICI uncompressed (the reference's NCCL tier is uncompressed too);
        # compression applies to the DCN wire, where the summation servers
        # decompress→fp32-sum→recompress (SURVEY §2.2/§3.3). Only this
        # controller pushes the pod-sum per partition, which is what makes
        # the hybrid topology bandwidth-optimal (SURVEY §5.8).
        # Sharded-wire hierarchical tier (BYTEPS_HYBRID_SHARDED, default
        # on): REDUCE becomes an ICI reduce-SCATTER, each partition is
        # owned by one of the pod's BYTEPS_POD_CONTROLLERS controllers
        # (rendezvous hash) whose own NIC carries it over DCN — per-NIC
        # wire bytes divide by the controller count instead of H−1 NICs
        # idling — and an ALLGATHER tail reassembles the global sums
        # across the pod. Each controller is modeled by its own PSWorker
        # (own connections, pacer NIC, fault plan); with 1 controller the
        # graph is the same wire as before plus the scatter/gather pair,
        # pinned bit-exact against the unsharded path.
        from byteps_tpu.server import PSWorker

        n_ctl = max(1, cfg.pod_controllers) if cfg.hybrid_sharded else 1
        _state.psworkers = [PSWorker() for _ in range(n_ctl)]
        _state.psworker = _state.psworkers[0]
        _state.owners = OwnerTable(n_ctl, salt=cfg.owner_salt)
        if cfg.trace_on:
            # measure server_clock − local_clock per server (kPing RTT/2)
            # so merge_traces can align EVERY server's rows, not just
            # server 0's — cross-host clocks can differ by seconds each
            try:
                tracer.metadata["server_clock_offsets"] = {
                    str(sidx): _state.psworker.clock_offset_ns(sidx)
                    for sidx in range(max(1, cfg.num_server))
                }
            except Exception as e:  # noqa: BLE001 - tracing is best-effort
                log.warning("clock-offset probe failed: %s", e)
        # The credit is acquired at COMPRESS and released at PUSH exit
        # (releases_credit wire scope): on a slow/throttled DCN the PULL
        # direction costs as much as PUSH, and a completion-scoped
        # credit would let draining pulls starve later pushes — with
        # wire scope, COMPRESS of chunk i+1 runs while chunk i is on the
        # wire (credit ≥ 2) and at most ``credit`` encoded payloads are
        # ever buffered ahead of the wire.
        # PUSH/PULL are stage-retryable (chaos hardening): a mid-flight
        # failover re-runs the stage against the new server placement
        # instead of failing the Handle (docs/robustness.md).
        stages = [
            Stage("REDUCE", _reduce_stage, pool_size=1),
            Stage("COPYD2H", _d2h_stage, pool_size=2),
            Stage("COMPRESS", _compress_stage, credited=True,
                  pool_size=2),
            # +1 attempt per extra controller: a total-DCN-outage
            # walk-down spends one stage attempt failing each owner over
            # before the last controller may degrade
            Stage("PUSH", _dcn_push_stage, credited=True, pool_size=4,
                  releases_credit=True, retryable=True,
                  max_attempts=2 + n_ctl),
            Stage("PULL", _dcn_pull_stage, pool_size=4,
                  retryable=True, max_attempts=2 + n_ctl),
            Stage("DECOMPRESS", _decompress_stage, pool_size=2),
            Stage("COPYH2D", _h2d_stage, pool_size=2),
        ]
        if cfg.hybrid_sharded:
            # the hierarchical tail: H2D placed the pulled global sums as
            # per-device segments; the ICI all-gather replicates them
            # (reference BROADCAST after COPYH2D)
            stages.append(Stage("ALLGATHER", _allgather_stage, pool_size=2))
        # pinned against the canonical order trace_analysis sorts by
        # (stage_orders.HYBRID_STAGE_ORDER): a stage added here without
        # updating the shared constant is a bug, not a silent drift
        bps_check(
            tuple(s.name for s in stages)
            == HYBRID_STAGE_ORDER[:len(stages)],
            "hybrid stage list drifted from HYBRID_STAGE_ORDER")
        _state.scheduler = PipelineScheduler(
            stages=stages,
            credit=cfg.scheduling_credit,
            tracer=tracer,
            credit_scope="owner" if n_ctl > 1 else "global",
            # bounded staleness (BYTEPS_STALENESS=K): PUSH of round r+K
            # no longer gates on round r's PULL — a pipelining caller
            # keeps K+1 rounds of one key in flight and the window
            # bounds the run-ahead (docs/robustness.md §bounded
            # staleness)
            rounds_window=cfg.staleness if cfg.staleness > 0 else None,
        )
    else:
        # Eager ICI pipeline: PUSHPULL issues the jitted chunk collective
        # (async dispatch; issue order = execution order on the device
        # stream), SYNC blocks until the chunk's result is ready and frees
        # the credit.
        stages = [
            Stage("PUSHPULL", _dispatch_stage, credited=True, pool_size=1),
            Stage("SYNC", _sync_stage, pool_size=4),
        ]
        bps_check(
            tuple(s.name for s in stages) == EAGER_STAGE_ORDER,
            "eager stage list drifted from EAGER_STAGE_ORDER")
        _state.scheduler = PipelineScheduler(
            stages=stages,
            credit=cfg.scheduling_credit,
            tracer=tracer,
        )
    if cfg.auto_tune and cfg.is_distributed:
        # Credit-ONLY tuner in hybrid mode: credit is a purely local knob
        # (it changes this worker's issue parallelism, never the keys or
        # partition sizes the servers see), so per-worker moves are safe.
        # The partition knob stays off — per-worker tuners would
        # repartition at different times, pushing mismatched partition
        # sizes under the same keys. With wire-scoped credits (above),
        # credit is exactly the knob that trades pipeline overlap against
        # wire contention on a slow DCN.
        from byteps_tpu.common.tuner import AutoTuner

        log.info(
            "BYTEPS_AUTO_TUNE in distributed mode: tuning credit only "
            "(partition moves are not coordinated across workers)"
        )
        _state.tuner = AutoTuner(
            apply=lambda pb, cr: _state.scheduler.set_credit(cr),
            partition_bytes=cfg.partition_bytes,
            credit=cfg.scheduling_credit,
            knobs=("credit",),
        )
    elif cfg.auto_tune and not cfg.is_distributed:
        # ByteScheduler auto-tuner (BYTEPS_AUTO_TUNE=1): online hill-climb
        # of (partition_bytes, credit) on the eager path. Single-controller
        # only — all devices see one scheduler, so moves are consistent.
        from byteps_tpu.common.tuner import AutoTuner

        def _apply_tuning(pb: int, cr: int) -> None:
            _state.registry.repartition(pb)
            _state.scheduler.set_credit(cr)
            # EF/momentum buffers are shaped per partition; a repartition
            # invalidates them (the residual restarts from zero — same
            # effect as the reference re-instantiating compressors on
            # partition change)
            _state.ef_state.clear()
            _state.mom_state.clear()

        _state.tuner = AutoTuner(
            apply=_apply_tuning,
            partition_bytes=cfg.partition_bytes,
            credit=cfg.scheduling_credit,
        )
    else:
        _state.tuner = None
    _state.initialized = True
    log.info(
        "byteps_tpu.jax initialized: mesh=%s devices=%d compression=%s",
        dict(_state.mesh.shape), size(), _state.spec.compressor.name,
    )


def shutdown() -> None:
    """Reference: ``byteps_shutdown``."""
    if _state.scheduler is not None:
        _state.scheduler.shutdown()
    if _state.psworker is not None:
        # one kShutdown round per pod (servers count pods, and all of a
        # pod's controller NICs share its worker id); extra NICs retire
        # (counters folded into the trace under a per-NIC tag)
        from byteps_tpu.server import retire_nic

        for rank, w in enumerate(_state.psworkers[1:], start=1):
            retire_nic(w, rank)
        _state.psworker.shutdown()
        _state.psworker = None
        _state.psworkers = []
        _state.owners = None
    tracer = get_tracer()
    if tracer.enabled:
        # after the pipeline stops so late stage events are included; runs
        # shorter than BYTEPS_TRACE_END_STEP still get their trace
        tracer.dump()
    _state.initialized = False
    _state.versions.clear()
    _state.ef_state.clear()
    _state.mom_state.clear()
    _state.inited_keys.clear()
    _state.membership_hooks.clear()


def _require_init() -> None:
    bps_check(_state.initialized, "call byteps_tpu.jax.init() first")


# --- topology queries (reference: byteps_rank/size/local_rank/local_size) ---
def rank() -> int:
    """This controller's worker id (0 on a single-host job)."""
    _require_init()
    return _state.cfg.worker_id


def pod_size() -> int:
    """Devices on this controller's dp axis (one pod / reference machine)."""
    _require_init()
    return _state.mesh.shape[_state.cfg.dp_axis]


def size() -> int:
    """Global data-parallel participant count (each TPU device is the
    analog of one reference GPU worker): pod devices × DMLC_NUM_WORKER
    pods. Matches the reference's size() = machines × local GPUs. In
    global-mesh mode the mesh already spans every host's devices, so
    pod_size() IS the global count."""
    _require_init()
    if _state.cfg.jax_distributed:
        return pod_size()
    return pod_size() * max(1, _state.cfg.num_worker)


def local_rank() -> int:
    _require_init()
    return _state.cfg.local_rank


def local_size() -> int:
    _require_init()
    return jax.local_device_count()


def mesh():
    _require_init()
    return _state.mesh


# --- eager push_pull path ---------------------------------------------------
def _global_rows(local_rows: np.ndarray, n: int) -> jax.Array:
    """Assemble per-process local-device rows into one (n, L) global array
    sharded over the dp axis (global-mesh mode: each controller holds only
    its own devices' rows)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(_state.mesh, P(_state.cfg.dp_axis))
    return jax.make_array_from_process_local_data(
        sh, np.asarray(local_rows), (n,) + local_rows.shape[1:]
    )


def _tensor_rng(name: str, version: int, seed: int = 0):
    # zlib.crc32 is stable across processes/runs, unlike salted hash() —
    # multi-host controllers must derive identical keys for the same tensor
    # (randomk index agreement).
    import zlib

    base = jax.random.fold_in(_state.base_rng, zlib.crc32(name.encode()) & 0x7FFFFFFF)
    base = jax.random.fold_in(base, seed)
    return jax.random.fold_in(base, version)


def _dispatch_stage(task: PartitionTask):
    """Issue the chunk collective (returns an in-flight jax array).

    Applies the reference compression pipeline per partition: Nesterov
    momentum → error feedback → compress → exchange (the decorator order of
    the reference's momentum/EF wrappers around the base compressor).
    """
    x = task.context["x2d"]
    p = task.partition
    chunk = jax.lax.slice_in_dim(x, p.offset, p.offset + p.length, axis=1)
    spec = task.context["spec"]
    average = task.context["average"]
    if not spec.enabled:
        return allreduce_flat(
            chunk, _state.mesh, _state.cfg.dp_axis, average=average
        )
    rng = jax.random.fold_in(task.context["rng"], p.part_idx)
    skey = (task.name, p.part_idx)
    if spec.momentum:
        m = _state.mom_state.get(skey)
        if m is None:
            m = jnp.zeros_like(chunk, dtype=jnp.float32)
        chunk, m = momentum_step(chunk.astype(jnp.float32), m, spec.mu)
        _state.mom_state[skey] = m
    if spec.ef:
        e = _state.ef_state.get(skey)
        if e is None:
            e = jnp.zeros_like(chunk, dtype=jnp.float32)
        out, new_e = compressed_allreduce_flat(
            chunk, spec.compressor, _state.mesh, _state.cfg.dp_axis,
            average=average, rng=rng, two_way=spec.two_way, ef_residual=e,
        )
        _state.ef_state[skey] = new_e
        return out
    return compressed_allreduce_flat(
        chunk, spec.compressor, _state.mesh, _state.cfg.dp_axis,
        average=average, rng=rng, two_way=spec.two_way,
    )


def _sync_stage(task: PartitionTask):
    out = task.payload
    out.block_until_ready()
    return out


# --- hybrid (distributed) pipeline stages -----------------------------------
def _reduce_stage(task: PartitionTask):
    """Intra-pod ICI sum of this chunk (async dispatch; reference REDUCE).

    Sharded-wire mode reduce-SCATTERs instead: each device ends up
    holding its segment of the pod sum — half the ICI bytes of a full
    allreduce (the ALLGATHER tail pays the other half AFTER the DCN round
    trip, reassembling the *global* sums), and on a multi-host pod each
    controller then only d2h's its own segments.

    Under ``BYTEPS_ICI_TIER=ring`` (the ici-compressed wire tier) a
    compressed job's qualifying partitions ride the compressed ring
    collective instead of the raw psum: compressed bytes on the ICI
    links, pod sums approximated by the codec (Σ D(C(g)) in fp32 —
    stateless at this hop; the DCN tier's EF keeps recirculating its own
    wire error as before). The layout contract is unchanged — same
    padded ``(n·ceil(L/n),)`` scattered form (or replicated ``(L,)``
    unsharded), so COPYD2H/DECOMPRESS/ALLGATHER need no changes."""
    x = task.context["x2d"]
    p = task.partition
    chunk = jax.lax.slice_in_dim(x, p.offset, p.offset + p.length, axis=1)
    cfg = _state.cfg
    spec = task.context["spec"]
    ici_compressed = (
        cfg.ici_tier == "ring" and spec.enabled and pod_size() > 1
        and p.length * 4 >= cfg.min_compress_bytes
    )
    with _state.ici_lock:
        if ici_compressed:
            rng = jax.random.fold_in(task.context["rng"], p.part_idx)
            if cfg.hybrid_sharded:
                return compressed_reduce_scatter_flat(
                    chunk, spec.compressor, _state.mesh, cfg.dp_axis,
                    average=False, rng=rng, tier="ring")
            return compressed_allreduce_flat(
                chunk, spec.compressor, _state.mesh, cfg.dp_axis,
                average=False, rng=rng, two_way=spec.two_way, tier="ring")
        if cfg.hybrid_sharded:
            return reduce_scatter_flat(chunk, _state.mesh, cfg.dp_axis)
        return allreduce_flat(chunk, _state.mesh, cfg.dp_axis,
                              average=False)


def _d2h_stage(task: PartitionTask):
    """Device→host for the DCN wire (reference COPYD2H; pool threads give
    the double-buffering the reference gets from pinned shm).

    ``jax.device_get`` instead of ``np.asarray(..., dtype=np.float32)``:
    on a CPU-backed buffer the old spelling could cast-copy a second
    time; device_get hands back the transferred (or zero-copy host) f32
    buffer directly. The scattered REDUCE output may be padded to
    n·ceil(L/n) — trim to the partition. Contract (pinned in
    tests/test_sharded_hybrid.py): f32 and C-contiguous always; writable
    whenever EF/momentum are configured, so the COMPRESS stage's state
    arithmetic may mutate in place — a read-only zero-copy view is only
    ever returned on the stateless path."""
    out = jax.device_get(task.payload)
    out = out.reshape(-1)[: task.partition.length]
    spec = task.context["spec"]
    needs_write = spec.enabled and (spec.ef or spec.momentum)
    if (out.dtype != np.float32 or not out.flags.c_contiguous
            or (needs_write and not out.flags.writeable)):
        out = np.ascontiguousarray(out, dtype=np.float32)
        if needs_write and not out.flags.writeable:
            out = out.copy()
    return out


def _wire_seed(task: PartitionTask) -> int:
    """Deterministic per (tensor, version, partition) seed shared by the
    COMPRESS and DECOMPRESS stages on every pod — the reference's
    synchronized compressor PRNG (randomk index agreement, dithering).
    One definition for every path: compression/wire.py wire_seed (the
    host DcnCore derives the same seed at salt 0)."""
    from byteps_tpu.compression.wire import wire_seed

    return wire_seed(task.name, task.context["version"],
                     task.partition.part_idx,
                     salt=task.context["spec"].seed)


def _compress_stage(task: PartitionTask):
    """Host-side momentum → error-feedback → wire encode (reference
    COMPRESS stage, core_loops.cc RunCompressLoopOnce; the decorator order
    matches the reference's momentum/EF wrappers around the compressor)."""
    p = task.partition
    plan = task.context["plans"][p.part_idx]
    x = task.payload  # np fp32 pod-sum
    if plan is None:
        return x.view(np.uint8).ravel()
    spec = task.context["spec"]
    seed = _wire_seed(task)
    skey = (task.name, p.part_idx)
    # _fail_owner resets EF/momentum for partitions whose owner moved; a
    # compress that read its buffers BEFORE that reset must not write them
    # back after it (the stale residual would silently resurrect). Writes
    # are dropped if the generation moved between read and write-back —
    # losing one best-effort residual update beats racing the reset.
    gen = _state.failover_gen
    if spec.momentum:
        m = _state.mom_state.get(skey)
        if m is None:
            m = np.zeros_like(x)
        m_new = spec.mu * m + x
        x = x + spec.mu * m_new
        with _state.lock:
            if _state.failover_gen == gen:
                _state.mom_state[skey] = m_new
    if spec.ef:
        e = _state.ef_state.get(skey)
        if e is None:
            e = np.zeros_like(x)
        corrected = x + e
        payload = plan.codec.encode(corrected, seed)
        approx = plan.codec.decode(payload, x.size, seed)
        with _state.lock:
            if _state.failover_gen == gen:
                _state.ef_state[skey] = corrected - approx
        return payload
    return plan.codec.encode(x, seed)


def _owner_of(key: int) -> int:
    return _state.owners.owner(key) if _state.owners is not None else 0


def _stall_diag():
    """Handle.diag callback for the hybrid tier — the same assembly as
    DcnCore's (`dcn_adapter.stall_diag`), so StallError reports from the
    two pipelines carry identical diagnostics."""
    from byteps_tpu.common.dcn_adapter import stall_diag

    return stall_diag(_state.psworkers, _state.owners, _state.scheduler)


def _fail_owner(rank: int, cause: Optional[BaseException] = None) -> bool:
    """Jax-side owner failover (mirrors DcnCore.fail_owner; the shared
    fence → export → adopt → shrink critical section is
    :func:`byteps_tpu.server.hand_off_owner`), then reset EF/momentum
    state for every partition whose owner moved — per-owner compressor
    state does not migrate off a dead controller; the residual restarts
    from zero with the remap, exactly like a PR3 key remap."""
    from byteps_tpu.server import hand_off_owner

    with _state.lock:
        live = hand_off_owner(_state.psworkers, _state.owners, rank)
        if live is None:
            return False
        new_live = _state.owners.live()
        moved = set()
        for name, ctx in _state.registry.snapshot():
            for part in ctx.partitions:
                if _state.owners.owner_in(part.key, live) == rank:
                    moved.add((name, part.part_idx))
        for skey in moved:
            _state.ef_state.pop(skey, None)
            _state.mom_state.pop(skey, None)
        # invalidate write-backs from any COMPRESS that read its state
        # before this reset (see _compress_stage)
        _state.failover_gen += 1
        _state.owner_failovers += 1
    if rank != 0:
        # free the dead NIC (monitor thread, connections, pacer) — worker
        # 0 stays open, fenced: it carries the pod's kShutdown round. The
        # dead NIC's counters (the faults that killed it) fold into the
        # trace first — close() alone would drop them.
        from byteps_tpu.server import retire_nic

        retire_nic(_state.psworkers[rank], rank)
    get_tracer().instant("owner_failover", "FAULT",
                         {"owner": rank, "survivors": sorted(new_live),
                          "cause": type(cause).__name__ if cause else None})
    log.warning(
        "pod controller %d gave up its wire (%s); %d partition state "
        "buffer(s) reset, partitions remap to owners %s", rank,
        cause if cause is not None else "requested", len(moved),
        sorted(new_live))
    return True


def _owner_giveup(task: PartitionTask, owner: int, e: BaseException):
    """Retry-exhausted wire error through ``owner``'s NIC: fail it over
    and re-raise stage-retryably so the re-run lands on a survivor."""
    from byteps_tpu.common.dcn_adapter import (
        owner_wire_death,
        remap_dead_owner,
    )

    if len(_state.psworkers) > 1 and owner_wire_death(e):
        remap_dead_owner(task, owner, _state.owners, _fail_owner,
                         _owner_of, e, "wire dead")
    raise e


def _dcn_push_stage(task: PartitionTask):
    p = task.partition
    owner = _owner_of(p.key)
    worker = _state.psworkers[owner]
    if not worker.has_live_servers():
        # THIS NIC sees zero live servers — with sibling NICs alive that
        # is the OWNER's link dying (per-PSWorker health monitors ping
        # through their own connections), so fail the owner over before
        # degrading; a genuine total outage walks down to the last
        # controller, which degrades as before.
        from byteps_tpu.common.dcn_adapter import remap_dead_owner
        from byteps_tpu.server import NoLiveServersError

        if len(_state.psworkers) > 1:
            remap_dead_owner(
                task, owner, _state.owners, _fail_owner, _owner_of,
                NoLiveServersError(f"owner {owner} sees no live servers"),
                "lost all servers")
        # total DCN outage: the payload is already the pod's pure-ICI sum
        # (REDUCE stage), so degrade to it instead of failing the handle —
        # cross-pod aggregation is lost, intra-pod training continues
        # (docs/robustness.md; gated by BYTEPS_DEGRADED_OK)
        from byteps_tpu.common.dcn_adapter import degraded_fallback

        return degraded_fallback(
            worker, _state.cfg, task, log,
            "the pure-ICI (pod-local) allreduce")
    plan = task.context["plans"][p.part_idx]
    store_bytes = (
        plan.codec.store_elems(p.length) * 4 if plan is not None
        else p.length * 4
    )
    with _state.lock:
        needs_init = (owner, p.key) not in _state.inited_keys
    try:
        if needs_init:
            # marked inited only AFTER success: a failed init whose stage
            # retries must re-run it, not be skipped forever (every later
            # push would then hit an uninitialized server key); two racing
            # pushes both initing is harmless — server init is idempotent
            worker.init_key(p.key, store_bytes)
            with _state.lock:
                _state.inited_keys.add((owner, p.key))
        codec_id = plan.codec.codec_id if plan is not None else 0
        # pin the round BEFORE the wire attempt (see DcnCore._push_stage
        # for the full why): a stage retry — possibly via a surviving
        # owner after a failover — re-sends the SAME round, which the
        # server either sums (never arrived) or dedupes (ack lost)
        task.push_version = worker.mint_version(
            p.key, getattr(task, "push_version", None))
        version = worker.push_bytes(
            p.key, task.payload, codec_id,
            version=task.push_version)
    except BaseException as e:  # noqa: BLE001 - owner-death classify
        from byteps_tpu.server import WorkerEvictedError

        if isinstance(e, WorkerEvictedError):
            # rejoin adopted the server watermarks; the stage retry must
            # mint a FRESH round (a stale pin would be dedupe-dropped —
            # see DcnCore._push_stage)
            task.push_version = None
        _owner_giveup(task, owner, e)
    task.push_version = version
    return version


def _dcn_pull_stage(task: PartitionTask):
    from byteps_tpu.common.dcn_adapter import DegradedLocal

    p = task.partition
    if isinstance(task.payload, DegradedLocal):
        return task.payload.payload
    plan = task.context["plans"][p.part_idx]
    owner = _owner_of(p.key)
    worker = _state.psworkers[owner]
    try:
        if plan is None:
            out = worker.pull_bytes(p.key, p.length * 4, task.payload, 0)
        else:
            out = worker.pull_bytes(
                p.key, plan.pull_capacity(p.length), task.payload,
                plan.pull_codec_id,
            )
        # the round's OWN live count (from its response's epoch stamp):
        # the averaging divisor for THIS partition, even if the current
        # membership has already moved on
        task.round_live = worker.last_round_live()
        # the round the server actually SERVED (bounded staleness may
        # answer up to K rounds behind the requested one) — DECOMPRESS
        # keys its seed off it so the aggregate decodes with the round
        # it was built from
        task.served_round = worker.last_pull_round()
        return out
    except BaseException as e:  # noqa: BLE001 - owner-death classify
        _owner_giveup(task, owner, e)


def _decompress_stage(task: PartitionTask):
    """Wire decode of the pulled round result (reference DECOMPRESS stage)."""
    p = task.partition
    plan = task.context["plans"][p.part_idx]
    buf = task.payload
    if plan is None:
        return np.ascontiguousarray(buf).view(np.float32).copy()
    if getattr(task, "degraded", False):
        # degraded payload is the PUSH-side encoding (the pull wire
        # format never existed for this round)
        return plan.codec.decode(np.ascontiguousarray(buf), p.length,
                                 _wire_seed(task))
    # the served round may trail the requested one under bounded
    # staleness — pull_seed owns the served-round → seed contract
    from byteps_tpu.compression.wire import pull_seed

    seed = pull_seed(task.name, task.context["version"], p.part_idx,
                     served_round=getattr(task, "served_round", None),
                     staleness=_state.cfg.staleness,
                     salt=task.context["spec"].seed)
    return plan.decode_pull(np.ascontiguousarray(buf), p.length, seed)


def _live_size() -> int:
    """Global participant count under ELASTIC membership: pod devices ×
    live pods per the most recently adopted membership epoch. Equals
    ``size()`` while the membership is full; after an eviction the pull
    results are sums over the live set (the server's quorum scaling keeps
    them unbiased), so averaging must divide by the live count — every
    worker adopts the same epoch, so the rescale is consistent across the
    survivors."""
    if _state.cfg.jax_distributed or not _state.psworkers:
        return size()
    return pod_size() * max(1, min(w.live_pods()
                                   for w in _state.psworkers))


# -- scale-up elasticity (mid-stream join; docs/robustness.md §scale-up) -----
def on_membership_change(hook) -> None:
    """Register ``hook(live_pods)`` to run after this process adopts a
    membership change through :func:`join`. This is where the elastic
    data-shard reassignment (``byteps_tpu.data.ElasticShardMap.assign``
    over the live set) and the LR/batch rescale policy
    (:func:`linear_scale`) hang — the framework owns the protocol event,
    the hooks own the training-semantics response."""
    _require_init()
    _state.membership_hooks.append(hook)


def join() -> int:
    """Mid-stream scale-UP: admit this worker into a RUNNING job — the
    counterpart of the eviction/rejoin machinery. Runs the kJoin
    admission + kRounds watermark adoption on every live summation
    server for each controller NIC (all share the pod's worker id), so
    the pod enters at a round boundary: the membership epoch bumps
    (peers adopt it on their next op and rescale their averaging
    divisor), rounds open at admission close over their contributors,
    and this pod's first push continues the server's round sequence at
    the served-round frontier. Fires the registered
    :func:`on_membership_change` hooks with the adopted live pod count
    and returns it. On the collectives-only path (no PS tier) the hooks
    still fire — membership there is ``jax.distributed``'s problem, but
    shard/LR policies remain the caller's."""
    _require_init()
    if _state.psworkers:
        for w in _state.psworkers:
            w.join()
    live = _live_size()
    for hook in list(_state.membership_hooks):
        hook(live)
    return live


def linear_scale(base: float, base_live: int, live: int) -> float:
    """The standard linear LR/batch rescale policy for elastic
    membership (Goyal et al.'s linear scaling rule applied to the LIVE
    worker count): ``base`` was tuned at ``base_live`` participants, the
    job now has ``live`` — scale proportionally. Offered as the default
    :func:`on_membership_change` policy; jobs with warmup or LARS-style
    schedules plug their own."""
    return base * (live / max(1, base_live))


def _average_h2d(task: PartitionTask, out: jnp.ndarray) -> jnp.ndarray:
    if task.context["average"]:
        if getattr(task, "degraded", False):
            # pod average: an unbiased estimate of the global average
            # (the pods the fallback cannot reach would have contributed
            # pod-sums of the same expected scale)
            out = out / pod_size()
        else:
            # divisor = the pulled round's OWN live membership (its
            # response carried the epoch it closed under); fall back to
            # the currently adopted count for non-elastic paths
            live = getattr(task, "round_live", None)
            d = (pod_size() * max(1, live) if live is not None
                 else _live_size())
            out = out / d
    return out


def _h2d_stage(task: PartitionTask):
    """Host→device of the pulled global sum (reference COPYH2D).

    Sharded-wire mode places it as per-device SEGMENTS over the dp axis —
    each device receives ~1/n of the partition over PCIe — and the
    ALLGATHER tail stage replicates them over ICI (the reference's
    BROADCAST). Unsharded keeps the replicated put + averaging here."""
    if not _state.cfg.hybrid_sharded:
        return _average_h2d(task, jnp.asarray(task.payload))
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = pod_size()
    L = task.partition.length
    seg = -(-L // n)
    host = np.asarray(task.payload, dtype=np.float32)
    if seg * n != L:
        host = np.pad(host, (0, seg * n - L))
    sh = NamedSharding(_state.mesh, P(_state.cfg.dp_axis))
    return jax.device_put(host, sh)


def _allgather_stage(task: PartitionTask):
    """Sharded-wire tail: replicate the per-device segments across the
    pod (exact — a gather moves bits, never sums) and apply the
    averaging scale the unsharded graph applies at H2D."""
    with _state.ici_lock:  # pin collective dispatch order (see ici_lock)
        out = all_gather_flat(task.payload, _state.mesh,
                              _state.cfg.dp_axis,
                              length=task.partition.length)
    # averaging is elementwise — no collective, so dispatch it outside
    # the lock rather than serializing against REDUCE's dispatch
    return _average_h2d(task, out)


def push_pull_async(
    x: jnp.ndarray,
    average: bool = True,
    name: Optional[str] = None,
    priority: Optional[int] = None,
    compression_params: Optional[Dict[str, Any]] = None,
) -> Handle:
    """Asynchronously all-reduce a stacked per-device tensor.

    ``x`` has shape ``(pod_size(), ...)``, row d = local device d's value
    (the analog of one reference worker's GPU buffer), ideally sharded over
    the dp axis. In hybrid mode the result additionally sums across the
    ``DMLC_NUM_WORKER`` pods (``average=True`` divides by the global
    ``size()``). Returns a Handle; ``handle.wait()`` / :func:`synchronize`.

    Reference: ``byteps_push_pull`` / ``byteps_torch_push_pull_async``.

    In global-mesh mode (``BYTEPS_JAX_DISTRIBUTED``) across several
    controller processes, pass either the full global ``(size(), ...)``
    array or just THIS process's local-device rows
    ``(jax.local_device_count(), ...)`` — local rows are assembled into one
    dp-sharded global array before the collective.
    """
    _require_init()
    from byteps_tpu.comm.distributed import is_multiprocess

    n = pod_size()
    multiproc = is_multiprocess()
    if multiproc:
        n_local = jax.local_device_count()
        bps_check(
            x.ndim >= 1 and x.shape[0] in (n, n_local),
            f"expected leading axis {n} (global) or {n_local} (local "
            f"devices), got {x.shape}",
        )
    else:
        bps_check(x.ndim >= 1 and x.shape[0] == n,
                  f"expected leading axis {n} (= pod_size()), got {x.shape}")
    anonymous = name is None
    with _state.lock:
        if anonymous:
            name = f"byteps_push_pull.anon_{_state.anon_counter}"
            _state.anon_counter += 1
    inner_shape = x.shape[1:]
    L = int(np.prod(inner_shape)) if inner_shape else 1
    ctx = _state.registry.declare(name, (L,), np.dtype(x.dtype))
    with _state.lock:
        version = _state.versions.get(name, 0)
        _state.versions[name] = version + 1
    # auto step detection: the highest round number any tensor has reached
    # IS the training step — BYTEPS_TRACE_ON=1 alone records, no user code
    get_tracer().advance_to(version + 1)
    spec = (
        from_params(compression_params)
        if compression_params is not None
        else _state.spec
    )
    if anonymous and spec.enabled and (spec.ef or spec.momentum):
        # EF/momentum are per-tensor persistent state keyed by name; a fresh
        # anonymous name every call would never accumulate (EF silently off)
        # while leaking one gradient-sized buffer per call into the state
        # dicts. The reference requires named tensors for the same reason
        # (per-tensor compressor instances in BPSContext).
        import dataclasses as _dc

        if not getattr(push_pull_async, "_warned_anon_state", False):
            log.warning(
                "push_pull called without name= while %s is configured: "
                "error-feedback/momentum need a stable tensor name to "
                "persist state — disabled for anonymous tensors",
                spec.compressor.name,
            )
            push_pull_async._warned_anon_state = True  # type: ignore[attr-defined]
        spec = _dc.replace(spec, ef=False, momentum=False)
    plans = None
    if _state.cfg.is_distributed:
        # Hybrid mode compresses the DCN wire per partition (the server
        # decompresses, fp32-sums, recompresses). Partitions below
        # BYTEPS_MIN_COMPRESS_BYTES ride raw fp32 — tiny chunks expand
        # under onebit's word floor and aren't worth the codec time.
        from byteps_tpu.compression.wire import WirePlan, make_wire_codec

        codec = None
        if spec.enabled:
            try:
                codec = make_wire_codec(spec)
            except ValueError:
                # custom registry compressors without a DCN byte format
                # degrade to fp32 on the wire instead of crashing the job
                if not getattr(push_pull_async, "_warned_nowire", False):
                    log.warning(
                        "compressor '%s' has no DCN wire codec — hybrid "
                        "pushes for it ride fp32", spec.compressor.name,
                    )
                    push_pull_async._warned_nowire = True  # type: ignore[attr-defined]
        plans = [
            None
            if codec is None
            or p.length * 4 < _state.cfg.min_compress_bytes
            else WirePlan(codec, spec.two_way)
            for p in ctx.partitions
        ]
    # Skip compression for tiny tensors (reference: BYTEPS_MIN_COMPRESS_BYTES)
    elif spec.enabled and L * np.dtype(x.dtype).itemsize < _state.cfg.min_compress_bytes:
        spec = from_params(None)
    if multiproc and x.shape[0] != n:
        x2d = _global_rows(np.asarray(x).reshape(x.shape[0], L), n)
    else:
        x2d = x.reshape(n, L)
    handle = Handle(name, len(ctx.partitions))
    handle.inner_shape = inner_shape  # type: ignore[attr-defined]
    handle.dtype = x.dtype            # type: ignore[attr-defined]
    if _state.psworkers:
        handle.diag = _stall_diag  # StallError diagnostics (hybrid tier)
    shared = {
        "x2d": x2d,
        "spec": spec,
        "average": average,
        "version": version,
        "plans": plans,
        "rng": _tensor_rng(name, version, spec.seed),
    }
    tasks = []
    for p in ctx.partitions:
        overrides: Dict[str, Any] = {}
        if priority is not None:
            overrides["priority"] = priority  # override declaration order
        if _state.owners is not None:
            # owner label = placement at enqueue time (credit-pool
            # identity / trace attribution); stages re-resolve live
            overrides["owner"] = _state.owners.owner(p.key)
        if overrides:
            p = dataclasses.replace(p, **overrides)
        tasks.append(
            PartitionTask(partition=p, name=name, handle=handle,
                          context=shared, round=version)
        )
    if multiproc:
        # SPMD determinism: every controller must issue IDENTICAL
        # collectives in IDENTICAL order or the job deadlocks. The credit
        # scheduler's pop order is timing-dependent (credits free on
        # device-side completion), so in global-mesh mode chunks dispatch
        # inline in partition order — JAX's async dispatch still overlaps
        # their execution; only the issue order is pinned.
        handle.localize = True  # type: ignore[attr-defined]
        tracer = get_tracer()
        for t in tasks:
            with tracer.span(
                f"{name}.p{t.partition.part_idx}", "PUSHPULL",
                args={"key": t.partition.key,
                      "priority": t.partition.priority,
                      "length": t.partition.length},
            ):
                result = _dispatch_stage(t)
            handle._partition_done(t.partition.part_idx, result)
        return handle
    _state.scheduler.enqueue(tasks)
    return handle


def synchronize(handle: Handle, timeout: Optional[float] = 120.0) -> jnp.ndarray:
    """Wait for a handle and assemble the replicated result.

    Reference: ``synchronize()``/``wait_and_clear`` in byteps/torch.
    """
    results = handle.wait(timeout)
    parts = [results[i] for i in sorted(results)]
    if getattr(handle, "localize", False):
        # global-mesh mode: chunk results are mesh-wide replicated arrays;
        # hand the caller an ordinary process-local value (the Horovod-style
        # eager contract — usable in plain per-device computation, exactly
        # like the reference's in-place updated GPU tensor)
        flat_np = (np.asarray(parts[0]) if len(parts) == 1
                   else np.concatenate([np.asarray(p) for p in parts]))
        out = jnp.asarray(flat_np.reshape(handle.inner_shape))  # type: ignore[attr-defined]
        return out.astype(handle.dtype)     # type: ignore[attr-defined]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    out = flat.reshape(handle.inner_shape)  # type: ignore[attr-defined]
    return out.astype(handle.dtype)         # type: ignore[attr-defined]


def push_pull(
    x: jnp.ndarray,
    average: bool = True,
    name: Optional[str] = None,
    priority: Optional[int] = None,
    compression_params: Optional[Dict[str, Any]] = None,
) -> jnp.ndarray:
    """Blocking push_pull (reference: ``push_pull(tensor, average, name)``)."""
    return synchronize(
        push_pull_async(x, average, name, priority, compression_params)
    )


def push_pull_tree(
    grads, average: bool = True, name_prefix: str = "grad",
) -> Any:
    """Eagerly aggregate a pytree of stacked (N, ...) gradients; tensors are
    declared in pytree order so earlier leaves get higher priority."""
    _require_init()
    leaves, treedef = jax.tree.flatten(grads)
    handles = [
        push_pull_async(leaf, average=average, name=f"{name_prefix}.{i}")
        for i, leaf in enumerate(leaves)
    ]
    outs = [synchronize(h) for h in handles]
    return jax.tree.unflatten(treedef, outs)


# --- broadcast (reference: broadcast_parameters / broadcast_optimizer_state) -
def broadcast_parameters(params, root_rank: int = 0):
    """Replicate global rank ``root_rank``'s row of stacked (n_pod, ...)
    leaves to everyone — returns the replicated pytree (functional, unlike
    the reference's in-place op). Implemented as zero-on-non-root + summed
    aggregation, the reference's own trick; in hybrid mode the sum crosses
    pods through the summation servers (rank = pod_id·pod_size + row)."""
    _require_init()
    n = pod_size()
    root_pod, root_row = divmod(root_rank, n)

    if _state.cfg.is_distributed:
        import zlib

        leaves, treedef = jax.tree.flatten(params)
        # Fixed key family per pytree signature: repeated broadcasts (the
        # periodic-broadcast workload) reuse the same tensor names — and so
        # the same server KeyStores and registry entries — instead of
        # minting a fresh c{N} family per call that grows server memory
        # without bound. Distinct structures (params vs optimizer state)
        # hash to distinct families; workers derive the signature from the
        # same pytree, so names agree across pods with no counter to align.
        sig_src = repr(treedef) + repr(
            [(tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves]
        )
        sig = zlib.crc32(sig_src.encode()) & 0xFFFFFFFF
        handles = []
        for i, leaf in enumerate(leaves):
            bps_check(leaf.shape[0] == n, f"leading axis must be {n}")
            if _state.cfg.worker_id == root_pod:
                mask = (jnp.arange(n) == root_row).reshape(
                    (n,) + (1,) * (leaf.ndim - 1))
                z = jnp.where(mask, leaf, jnp.zeros_like(leaf))
            else:
                z = jnp.zeros_like(leaf)
            # fp32 wire: int leaves survive exactly below 2^24; broadcasts
            # never ride a lossy codec (params must replicate bit-faithfully
            # even when gradient compression is configured globally)
            handles.append(push_pull_async(
                z, average=False,
                name=f"byteps_broadcast.s{sig:08x}.{i}",
                compression_params={}))
        outs = [synchronize(h) for h in handles]
        return jax.tree.unflatten(treedef, outs)

    from byteps_tpu.comm.distributed import is_multiprocess

    multiproc = is_multiprocess()

    def bcast(leaf):
        L = int(np.prod(leaf.shape[1:])) if leaf.ndim > 1 else 1
        if multiproc and leaf.shape[0] != n:
            flat2d = _global_rows(
                np.asarray(leaf).reshape(leaf.shape[0], L), n)
        else:
            bps_check(leaf.shape[0] == n, f"leading axis must be {n}")
            flat2d = leaf.reshape(n, L)
        # native dtype throughout: zero-plus-psum is exact for ints too,
        # and a float32 round-trip would corrupt int leaves > 2^24
        flat = broadcast_flat(
            flat2d, _state.mesh, root=root_rank, axis=_state.cfg.dp_axis,
        )
        if multiproc:  # hand back a process-local value (see synchronize)
            flat = jnp.asarray(np.asarray(flat))
        return flat.reshape(leaf.shape[1:])

    return jax.tree.map(bcast, params)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Parity alias: optimizer states are pytrees too."""
    return broadcast_parameters(opt_state, root_rank)


def tuner():
    """The active AutoTuner (or None): call ``tuner().record_step(secs)``
    once per training step to drive online (partition, credit) tuning."""
    _require_init()
    return _state.tuner


def auto_tune_enabled() -> bool:
    """True when BYTEPS_AUTO_TUNE=1 — build your fused step through
    :class:`AutoTunedStep` (the train-step factories in
    ``byteps_tpu.models.train`` do this automatically)."""
    return get_config().auto_tune


def default_partition_bytes() -> int:
    """The configured BYTEPS_PARTITION_BYTES (tuner starting point)."""
    return get_config().partition_bytes


def declare_tensor(name: str, shape, dtype) -> None:
    """Pre-declare to fix priority order explicitly (reference:
    ``byteps_declare_tensor``)."""
    _require_init()
    L = int(np.prod(shape)) if len(tuple(shape)) else 1
    _state.registry.declare(name, (L,), np.dtype(dtype))
