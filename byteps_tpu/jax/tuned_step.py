"""Auto-tuned fused train step: retrace at tuner-chosen partition sizes.

Reference analog: the ByteScheduler tuner adjusts partition size online
while training runs (bytescheduler/common/search.py, SOSP'19 §5). On the
reference's eager engine a move just changes how the next tensors are
sliced; on the fused jit path the partition size is baked into the traced
XLA program, so a move means a retrace. ``AutoTunedStep`` owns that
machinery: it keeps one jitted executable per visited partition size
(compiles are cached, the tuner's grid is small), times each step, feeds
the tuner, and swaps executables when the tuner moves.

Credit is not a fused-path knob — XLA schedules chunk-collective overlap
itself — so the tuner searches ``knobs=("partition",)``.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax

from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.tuner import AutoTuner

log = get_logger("jax.tuned_step")


class AutoTunedStep:
    """Callable wrapping ``build_jit(partition_bytes) -> jitted step``.

    While the tuner is searching, every call blocks until the step's outputs
    are ready so the measured wall time is the true step time (the same
    synchronization the reference's tuner imposes); once converged, calls
    pass through without blocking and async dispatch pipelining returns.
    The warmup skip inside :class:`AutoTuner` absorbs the compile cost of a
    fresh partition size, so a retrace never pollutes its own measurement.
    """

    def __init__(
        self,
        build_jit: Callable[[Optional[int]], Callable],
        partition_bytes: int,
        interval: int = 5,
        warmup: int = 3,
        min_gain: float = 0.02,
    ) -> None:
        self._build = build_jit
        self._compiled: Dict[int, Callable] = {}
        self._pb = partition_bytes
        self.retraces = 0
        self.tuner = AutoTuner(
            apply=self._apply,
            interval=interval,
            warmup=warmup,
            min_gain=min_gain,
            partition_bytes=partition_bytes,
            knobs=("partition",),
        )

    def _apply(self, pb: int, credit: int) -> None:
        if pb != self._pb:
            log.info(
                "tuner: fused step moving to partition=%dKB%s",
                pb >> 10,
                "" if pb in self._compiled else " (will retrace)",
            )
        self._pb = pb

    @property
    def partition_bytes(self) -> int:
        """The partition size the next call will run with."""
        return self._pb

    @property
    def compiled_partition_sizes(self):
        return sorted(self._compiled)

    def __call__(self, *args):
        # always-on train-step tick (docs/observability.md): the plain
        # jitted path gets this from _finalize_step's wrapper; the tuned
        # path must stay an AutoTunedStep instance, so it ticks itself
        # (relative — the recorder may already be ahead of this
        # instance's private step count)
        from byteps_tpu.common.flight_recorder import get_flight_recorder

        get_flight_recorder().tick()
        step = self._compiled.get(self._pb)
        if step is None:
            step = self._build(self._pb)
            self._compiled[self._pb] = step
            self.retraces += 1
        if self.tuner.converged:
            return step(*args)
        t0 = time.perf_counter()
        out = step(*args)
        jax.block_until_ready(out)
        self.tuner.record_step(time.perf_counter() - t0)
        return out
