"""Elastic data sharding: the shard map recomputed from the live bitmap.

Mid-stream worker churn (eviction, ``kJoin`` admission) changes WHO
consumes the input stream, but the epoch's data contract must not
change: within an epoch window no example may be dropped and none
visited twice. :class:`ElasticShardMap` is the deterministic shard
authority every worker holds a replica of — same ``(seed, epoch)`` ⇒
same global visit order on every host, so recomputing the assignment
from the adopted live set needs no coordination beyond the membership
epoch itself (exactly like the rendezvous-hashed key→server placement:
agreement through shared determinism, not messages).

Usage at an epoch adoption (a ``byteps_tpu.jax.on_membership_change``
hook, or the host adapters' own membership callbacks)::

    smap = ElasticShardMap(n_examples, seed=epoch_seed)
    shard = smap.shard_for(my_id, live_ids)      # consume in order...
    smap.advance(consumed)                       # ...at round boundaries
    # membership changed (join/evict): the UNVISITED remainder re-splits
    shard = smap.shard_for(my_id, new_live_ids)

Pinned invariants (tests/test_join.py):

* the union of all live workers' shards is EXACTLY the unvisited
  remainder of the epoch's global order — nothing dropped;
* shards are pairwise disjoint — nothing double-visited;
* the assignment is a pure function of ``(seed, epoch, cursor,
  live_ids)`` — every worker computes the same map independently.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence

import numpy as np

__all__ = ["ElasticShardMap", "live_ids_from_bitmap"]


def live_ids_from_bitmap(bitmap: Sequence[int]) -> List[int]:
    """Worker ids marked live in a ``kMembers`` bitmap (the server's
    per-worker live array) — the bridge from the membership layer's view
    to the shard map's ``live_ids`` argument."""
    return [i for i, b in enumerate(bitmap) if b]


class ElasticShardMap:
    """Deterministic elastic shard assignment over one epoch window."""

    def __init__(self, n_examples: int, seed: int = 0):
        if n_examples <= 0:
            raise ValueError(f"n_examples must be > 0, got {n_examples}")
        self.n_examples = int(n_examples)
        self.seed = int(seed)
        self.epoch = 0
        self._order = self._perm()
        self._cursor = 0

    def _perm(self) -> np.ndarray:
        # seeded by (seed, epoch): a fresh shuffle per epoch, identical
        # on every worker without coordination
        return np.random.default_rng(
            (self.seed, self.epoch)).permutation(self.n_examples)

    # -- epoch window cursor -------------------------------------------------
    @property
    def remaining(self) -> int:
        """Unvisited examples left in this epoch window."""
        return self.n_examples - self._cursor

    def advance(self, n: int) -> None:
        """Mark the next ``n`` examples of the GLOBAL order visited (call
        at round boundaries with the globally-consumed count — every
        worker advances identically, keeping the maps in agreement)."""
        if n < 0:
            raise ValueError(f"cannot advance by {n}")
        self._cursor = min(self.n_examples, self._cursor + int(n))

    def next_epoch(self) -> None:
        """Open the next epoch window: fresh deterministic shuffle, the
        cursor rewinds, and every example is visitable again."""
        self.epoch += 1
        self._order = self._perm()
        self._cursor = 0

    # -- assignment ----------------------------------------------------------
    def assign(self, live_ids: Iterable[int]) -> Dict[int, np.ndarray]:
        """Split the UNVISITED remainder of the epoch's global order over
        the live workers (contiguous near-equal chunks in ascending
        worker-id order). Recomputing after a membership change
        reassigns only what nobody has consumed yet — the visited prefix
        is never handed out again, so no example is dropped or
        double-visited within the epoch window."""
        ids = sorted({int(w) for w in live_ids})
        if not ids:
            raise ValueError("no live workers to shard the epoch over")
        chunks = np.array_split(self._order[self._cursor:], len(ids))
        return {w: chunks[i] for i, w in enumerate(ids)}

    def shard_for(self, worker_id: int,
                  live_ids: Iterable[int]) -> np.ndarray:
        """This worker's slice of :meth:`assign` (raises if it is not in
        the live set — an evicted worker holds no shard)."""
        shards = self.assign(live_ids)
        if int(worker_id) not in shards:
            raise ValueError(
                f"worker {worker_id} is not in the live set "
                f"{sorted(shards)} — evicted workers hold no shard")
        return shards[int(worker_id)]
