from byteps_tpu.data.loader import (
    PrefetchLoader,
    shard_batch,
)

__all__ = ["PrefetchLoader", "shard_batch"]
