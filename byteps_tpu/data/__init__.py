from byteps_tpu.data.elastic import (
    ElasticShardMap,
    live_ids_from_bitmap,
)
from byteps_tpu.data.loader import (
    PrefetchLoader,
    shard_batch,
)

__all__ = ["ElasticShardMap", "PrefetchLoader", "live_ids_from_bitmap",
           "shard_batch"]
