"""Input pipeline: sharded host→device prefetch.

The reference has no data loader of its own (SURVEY §1: models and data
come from the host framework — its examples use torch DataLoader /
tf.data). On TPU the host→device hop is the part the framework must own:
a training step that blocks on `device_put` serializes PCIe/DMA transfer
with MXU compute, and on a multi-host pod each controller must place its
process-local rows into one globally-sharded array. This module covers
both:

* :func:`shard_batch` — put one host batch (a pytree of numpy/jax
  arrays) onto a `NamedSharding`, using the process-local assembly path
  (`jax.make_array_from_process_local_data`) whenever the runtime spans
  several controllers (`BYTEPS_JAX_DISTRIBUTED` global-mesh mode).
* :class:`PrefetchLoader` — wraps any host-batch iterator and runs
  `shard_batch` in a background thread, keeping up to ``depth + 1``
  batches resident on device ahead of the consumer (``depth`` queued
  plus the one the producer holds while the queue is full), so batch
  t+1's H2D transfer rides under batch t's compute (the same overlap
  the reference gets from DataLoader worker processes + pinned-memory
  `cuda()` copies).

JAX dispatch is asynchronous, but `device_put` of a large host batch
still costs wall time on the dispatching thread (layout + DMA enqueue);
moving it off the training thread is what buys the overlap.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

import jax

from byteps_tpu.common.logging import get_logger

log = get_logger("data")


def _is_multiprocess() -> bool:
    try:
        return jax.process_count() > 1
    except RuntimeError:  # jax.distributed not initialized
        return False


def shard_batch(batch: Any, sharding: Any) -> Any:
    """Place a host batch (pytree) onto device(s) under ``sharding``.

    ``sharding`` is either one `jax.sharding.Sharding` applied to every
    leaf or a pytree of shardings matching ``batch``. Single-controller:
    plain `device_put`. Multi-controller (global-mesh mode): each leaf is
    this process's LOCAL rows; they are assembled into the global sharded
    array with `jax.make_array_from_process_local_data` (the data-parallel
    contract: every host feeds its own slice of the global batch).
    """
    one = isinstance(sharding, jax.sharding.Sharding)
    if _is_multiprocess():
        def put(x, s):
            return jax.make_array_from_process_local_data(s, x)
    else:
        def put(x, s):
            return jax.device_put(x, s)
    if one:
        return jax.tree.map(lambda x: put(x, sharding), batch)
    return jax.tree.map(put, batch, sharding)


class PrefetchLoader:
    """Iterate device-resident, sharded batches ``depth`` ahead of use.

    >>> loader = PrefetchLoader(host_batches, batch_sharding, depth=2)
    >>> for tokens, targets in loader:
    ...     loss, params, opt_state = step(params, opt_state, tokens, targets)

    The background thread stops at source exhaustion, on `close()`, or
    when an error occurs (re-raised in the consumer). Always a context
    manager; iterating twice is not supported (one pass per source
    iterator, like the reference's DataLoader epochs).
    """

    _DONE = object()

    def __init__(self, source: Iterable[Any], sharding: Any,
                 depth: int = 2) -> None:
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._source = iter(source)
        self._sharding = sharding
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="byteps-prefetch", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            for batch in self._source:
                if self._stop.is_set():
                    return
                dev = shard_batch(batch, self._sharding)
                # blocks when `depth` batches are already waiting — the
                # backpressure bounds residency at depth + 1 (this `dev`
                # plus the queue)
                while not self._stop.is_set():
                    try:
                        self._q.put(dev, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._err = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._DONE, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is not self._DONE and self._stop.is_set():
            # close() ran while we were blocked in get(): a producer that
            # was waiting on a full queue can win the drained slot, so the
            # item we just got may be a live batch and close()'s injected
            # _DONE may have been dropped — discard the stale batch and
            # end iteration instead of delivering data after close()
            raise StopIteration
        if item is self._DONE:
            # terminal: further __next__ calls must keep raising (the
            # producer is dead and will never put again). _err was set
            # BEFORE the producer's _DONE (its finally block), so no join
            # is needed for error surfacing — and a concurrent close()
            # injects _DONE while the producer may still be blocked inside
            # the user's source, where an unbounded join would hang this
            # consumer; the bounded join is best-effort cleanup only
            # (close() and the daemon flag handle the rest).
            self._stop.set()
            self._thread.join(timeout=1.0)
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop prefetching and release the thread (idempotent)."""
        self._stop.set()
        # unblock a producer waiting on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        # the drain above may have swallowed the producer's _DONE; put one
        # back so a consumer concurrently blocked in __next__'s q.get()
        # always unblocks (it re-checks _stop and raises StopIteration)
        try:
            self._q.put_nowait(self._DONE)
        except queue.Full:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "PrefetchLoader":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
