"""Trace-driven what-if simulator (dPRO, MLSys'22; ROADMAP item 3).

The joapolarbear fork exists to FEED its traces to dPRO, which replays
them to predict distributed-training performance under hypothetical
configurations — finishing the online-search story ByteScheduler started
with live coordinate descent. This package is that replay tier for the
TPU build: one recorded run (chrome trace + flight-recorder dump + the
run's resolved config, all of which now stamp themselves with
``Config.snapshot()``) is lifted into a calibrated cost model
(:mod:`~byteps_tpu.sim.extract`), replayed under any
:class:`~byteps_tpu.sim.engine.SimConfig` by a discrete-event engine
that re-expresses the scheduler's credit gates, per-owner pools,
rounds window, and the server's quorum/force-close round semantics as
event rules (:mod:`~byteps_tpu.sim.engine`), and searched
(:mod:`~byteps_tpu.sim.search`) so the AutoTuner and ScalingPolicy can
SOLVE for a config instead of sweeping it live.

Validation contract: ``bench.py --mode whatif`` replays one recorded
leg and must predict the measured medians of the other bench
configurations within 10% median error (docs/whatif.md).
"""

from byteps_tpu.sim.engine import SimConfig, SimResult, simulate
from byteps_tpu.sim.extract import (
    CostModel,
    calibrate_codecs,
    cost_model_from_events,
    cost_model_from_flight_dump,
)
from byteps_tpu.sim.search import (
    goodput_estimator,
    make_proposer,
    rank_configs,
)

__all__ = [
    "SimConfig", "SimResult", "simulate",
    "CostModel", "calibrate_codecs", "cost_model_from_events",
    "cost_model_from_flight_dump",
    "rank_configs", "make_proposer", "goodput_estimator",
]
