"""Discrete-event replay engine: one recorded run, any config.

The real data plane is deterministic where it matters (SURVEY §5.1 is
why the fork exists; ROADMAP item 3 is why this module does): wire time
is token-bucket arithmetic (``server/pacer.py``), codec bytes-on-wire
are closed-form per codec (``compression/wire.py``), and the scheduler's
issue rules — priority order, credit gates, per-owner pools, the
rounds window — are explicit state machines (``common/scheduler.py``).
This engine re-expresses those rules as simulation events over a
:class:`~byteps_tpu.sim.extract.CostModel` calibrated from one recorded
run, so a hypothetical :class:`SimConfig` (partition bytes × credits ×
codec × staleness K × wire tier rate × controller count × owner salt)
is *replayed*, not curve-fit.

What is REPLAYED (event rules copied from the production code):

* priority-ordered issue per stage, ties by key, skip-blocked-heads
  (``PipelineScheduler._pump`` / ``_StageQueue.pop_ready``);
* the credit budget — acquired at the first credited stage, wire-scoped
  release on PUSH exit (``Stage.releases_credit``), per-owner pools
  under ``pod_controllers > 1`` with rendezvous-hashed ownership
  (``common/partition.owner_for_key``, salt included);
* the per-key rounds window (``BYTEPS_STALENESS``): a task more than K
  rounds ahead of its key's oldest in-flight round is skipped, not
  head-blocked;
* the summation server's round ladder: a round closes when every live
  worker contributed, a pull for round v is served from the newest
  closed round ≥ max(1, v−K), and a pull past the bound FORCE-closes
  straggler-held rounds over whoever contributed — never an empty
  round (``server/csrc/server.cc`` ServeMin/ForceMin/ForceAdvance);
* the pacer's deficit token bucket, bit-for-bit (64 KB burst,
  per-direction, per-NIC): a charge at time t books its bytes and
  completes at t + max(0, −avail/rate).

What is MODELED (calibrated, not replayed): per-stage service times —
fixed per-task overhead plus a per-byte slope fit from the recorded
spans, with per-codec encode/decode throughputs micro-calibrated at
extract time for codecs the recorded run never exercised
(docs/whatif.md lists the assumptions).
"""

from __future__ import annotations

import dataclasses
import heapq
import random
from typing import Dict, List, Optional, Sequence, Tuple

from byteps_tpu.common.partition import owner_for_key
from byteps_tpu.common.stage_orders import (
    DCN_STAGE_ORDER,
    HYBRID_STAGE_ORDER,
)

# stage-name -> service kind; the graph itself comes from the declared
# stage orders (stage_orders.py), so a pipeline growing a stage shows up
# here as a KeyError instead of silently mis-simulating
_STAGE_KINDS = {
    "REDUCE": "compute", "COPYD2H": "compute", "COPYH2D": "compute",
    "ALLGATHER": "compute", "COMPRESS": "compress", "PUSH": "push",
    "PULL": "pull", "DECOMPRESS": "decompress",
    "PUSHPULL": "compute", "SYNC": "compute",
}
# DcnCore's constructor pool sizes (dcn_adapter.py stage list)
_POOL_SIZES = {"COMPRESS": 2, "PUSH": 4, "PULL": 4, "DECOMPRESS": 2}


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """One hypothetical configuration to replay the recorded run under.

    Mirrors the live knobs: ``BYTEPS_PARTITION_BYTES``,
    ``BYTEPS_SCHEDULING_CREDIT``, the wire codec,
    ``BYTEPS_DCN_THROTTLE_MBPS`` (0 = calibrated loopback rate),
    ``BYTEPS_STALENESS``, ``BYTEPS_POD_CONTROLLERS`` /
    ``BYTEPS_OWNER_SALT``, and the worker count. ``worker_speed``
    optionally slows individual workers (a 5× straggler is
    ``(1, 1, 5)``) for chaos-leg what-ifs. ``pipelined=None`` derives
    the enqueue policy from K: strict-sync callers enqueue round r+1
    after r assembles; bounded-staleness callers keep K+1 rounds in
    flight and the rounds window gates the run-ahead."""

    partition_bytes: int = 4096000
    credit: int = 4
    codec: str = "raw"
    throttle_mbps: float = 0.0
    staleness: int = 0
    pod_controllers: int = 1
    owner_salt: int = 0
    num_workers: int = 1
    rounds: int = 3
    two_way: bool = True
    pipelined: Optional[bool] = None
    worker_speed: Tuple[float, ...] = ()
    seed: int = 0
    jitter: float = 0.0

    def knobs(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SimResult:
    """Prediction for one (CostModel, SimConfig) replay."""

    step_time_s: float            # median per-round time (the headline)
    round_times_s: List[float]    # per-round completion deltas
    makespan_s: float             # first enqueue -> last completion
    tasks: int
    config: SimConfig
    stage_busy_s: Dict[str, float]
    # every stage issue as (t_s, stage, key, round, worker) in issue
    # order — what the scheduler-agreement tests pin against the real
    # PipelineScheduler's recorded order
    issues: List[Tuple[float, str, int, int, int]] = dataclasses.field(
        default_factory=list)

    def to_dict(self) -> Dict[str, object]:
        return {
            "step_time_s": round(self.step_time_s, 6),
            "round_times_s": [round(t, 6) for t in self.round_times_s],
            "makespan_s": round(self.makespan_s, 6),
            "tasks": self.tasks,
            "config": self.config.knobs(),
        }


class _Bucket:
    """The pacer's deficit token bucket on a virtual clock
    (``server/pacer.TokenBucket`` arithmetic, sleep -> completion time)."""

    __slots__ = ("rate", "burst", "avail", "last")

    def __init__(self, rate_bytes_per_s: float, burst: float = 64 << 10):
        self.rate = float(rate_bytes_per_s)
        self.burst = float(burst)
        self.avail = self.burst
        self.last = 0.0

    def charge(self, t: float, nbytes: float) -> float:
        """Book ``nbytes`` at time ``t``; returns the completion time."""
        if nbytes <= 0 or self.rate <= 0:
            return t
        self.avail = min(self.burst,
                         self.avail + (t - self.last) * self.rate)
        self.last = t
        self.avail -= nbytes
        return t + (-self.avail / self.rate if self.avail < 0 else 0.0)


class _Task:
    __slots__ = ("worker", "key", "part_idx", "length", "priority",
                 "round", "owner", "stage_idx", "holds_credit",
                 "credit_pool", "seq")

    def __init__(self, worker, key, part_idx, length, priority, rnd,
                 owner, seq):
        self.worker = worker
        self.key = key
        self.part_idx = part_idx
        self.length = length
        self.priority = priority
        self.round = rnd
        self.owner = owner
        self.stage_idx = 0
        self.holds_credit = False
        self.credit_pool = 0
        self.seq = seq

    @property
    def sort_key(self):
        # PipelineScheduler._StageQueue order: max priority first, ties
        # by key, FIFO within
        return (-self.priority, self.key, self.seq)


class _KeyStore:
    """Per-key server round ladder (server.cc KeyStore, timing only)."""

    __slots__ = ("closed", "arrived", "close_t", "parked")

    def __init__(self):
        self.closed = 0                 # newest closed round (1-based)
        self.arrived: Dict[int, set] = {}
        self.close_t = 0.0
        self.parked: List[tuple] = []   # (serve_min, task, issue_t)


class _WorkerState:
    """One worker's pipeline mirror: queues, busy counts, credit pools,
    per-key in-flight rounds, per-owner NIC buckets."""

    def __init__(self, cfg: SimConfig, n_stages: int, rate: float):
        self.queues: List[List[tuple]] = [[] for _ in range(n_stages)]
        self.busy = [0] * n_stages
        self.credit_total = max(1, cfg.credit)
        self.credits = self.credit_total
        self.owner_credits: Dict[int, int] = {}
        self.owner_scope = cfg.pod_controllers > 1
        self.key_rounds: Dict[int, set] = {}
        self.send = [_Bucket(rate) for _ in range(cfg.pod_controllers)]
        self.recv = [_Bucket(rate) for _ in range(cfg.pod_controllers)]
        self.round_remaining: Dict[int, int] = {}
        self.round_done_t: Dict[int, float] = {}
        self.round_enqueued = 0

    def credit_available(self, task: _Task) -> bool:
        if not self.owner_scope:
            return self.credits > 0
        return self.owner_credits.get(task.owner, self.credit_total) > 0

    def acquire_credit(self, task: _Task) -> None:
        task.holds_credit = True
        if not self.owner_scope:
            task.credit_pool = 0
            self.credits -= 1
            return
        task.credit_pool = task.owner
        self.owner_credits[task.owner] = self.owner_credits.get(
            task.owner, self.credit_total) - 1

    def release_credit(self, task: _Task) -> None:
        if not task.holds_credit:
            return
        task.holds_credit = False
        if not self.owner_scope:
            self.credits = min(self.credits + 1, self.credit_total)
            return
        pool = task.credit_pool
        self.owner_credits[pool] = min(
            self.owner_credits.get(pool, self.credit_total) + 1,
            self.credit_total)


def build_stages(pipeline: Sequence[str]) -> List[Tuple[str, str, int]]:
    """(name, kind, pool_size) rows for a declared stage order — the
    dependency graph is the pipeline order itself (each partition walks
    the stages in sequence; cross-partition edges come from the credit/
    pool/round gates)."""
    rows = []
    for name in pipeline:
        kind = _STAGE_KINDS[name]
        rows.append((name, kind, _POOL_SIZES.get(name, 2)))
    return rows


def simulate(model, cfg: SimConfig) -> SimResult:
    """Replay ``model`` (a :class:`~byteps_tpu.sim.extract.CostModel`)
    under ``cfg``. Pure and deterministic: same model + same config +
    same seed -> bit-identical result (pinned in tests/test_sim.py)."""
    pipeline = (DCN_STAGE_ORDER if model.pipeline == "dcn"
                else HYBRID_STAGE_ORDER)
    stages = build_stages(pipeline)
    n_stages = len(stages)
    n_workers = max(1, cfg.num_workers)
    rate = model.wire_rate_bps(cfg.throttle_mbps)
    rng = random.Random(cfg.seed)

    def jit() -> float:
        if cfg.jitter <= 0:
            return 1.0
        return 1.0 + cfg.jitter * (2.0 * rng.random() - 1.0)

    def speed(w: int) -> float:
        if w < len(cfg.worker_speed):
            return max(1e-9, float(cfg.worker_speed[w]))
        return 1.0

    workers = [_WorkerState(cfg, n_stages, rate) for _ in range(n_workers)]
    keystores: Dict[int, _KeyStore] = {}
    stage_busy_s: Dict[str, float] = {s[0]: 0.0 for s in stages}

    # partition layout under the hypothetical partition size
    parts = model.partition_layout(cfg.partition_bytes)
    tasks_per_round = len(parts)
    live = set(range(n_workers))
    k = max(0, int(cfg.staleness))
    pipelined = cfg.pipelined if cfg.pipelined is not None else k > 0
    rounds_window = k if k > 0 else None

    events: List[tuple] = []   # (t, seq, kind, payload)
    seq_counter = [0]

    def push_event(t: float, kind: str, payload) -> None:
        seq_counter[0] += 1
        heapq.heappush(events, (t, seq_counter[0], kind, payload))

    def ks(key: int) -> _KeyStore:
        st = keystores.get(key)
        if st is None:
            st = keystores[key] = _KeyStore()
        return st

    def enqueue_round(w: int, rnd: int, t: float) -> None:
        ws = workers[w]
        ws.round_remaining[rnd] = tasks_per_round
        ws.round_enqueued = max(ws.round_enqueued, rnd + 1)
        for (key, part_idx, length, priority) in parts:
            seq_counter[0] += 1
            task = _Task(w, key, part_idx, length, priority, rnd,
                         owner_for_key(key, set(range(cfg.pod_controllers)),
                                       cfg.owner_salt),
                         seq_counter[0])
            if rounds_window is not None:
                ws.key_rounds.setdefault(key, set()).add(rnd)
            heapq.heappush(ws.queues[0], (task.sort_key, task))

    def round_ready(ws: _WorkerState, task: _Task) -> bool:
        if rounds_window is None:
            return True
        rounds = ws.key_rounds.get(task.key)
        if not rounds:
            return True
        return task.round - min(rounds) <= rounds_window

    def pop_ready(ws: _WorkerState, si: int, credited: bool):
        """pop_ready semantics: highest-priority task passing the round
        window and (for credited stages) the credit gate; blocked heads
        are skipped, keeping their position."""
        q = ws.queues[si]
        skipped = []
        got = None
        while q:
            item = heapq.heappop(q)
            task = item[1]
            if round_ready(ws, task) and (
                    not credited or task.holds_credit
                    or ws.credit_available(task)):
                got = task
                break
            skipped.append(item)
        for it in skipped:
            heapq.heappush(q, it)
        return got

    # --- server round ladder (ServeMin / ForceMin / ForceAdvance) -----------
    def serve_min(v: int) -> int:
        return max(1, v - k) if k > 0 else v

    def force_min(v: int) -> int:
        return v - k if (k > 0 and v > k) else 0

    def release_parked(st: _KeyStore, t: float) -> None:
        if not st.parked:
            return
        still = []
        for (smin, task, issue_t) in st.parked:
            if st.closed >= smin:
                finish_pull(task, max(t, issue_t))
            else:
                still.append((smin, task, issue_t))
        st.parked = still

    def close_rounds(key: int, st: _KeyStore, upto: int, t: float) -> None:
        """FORCE-close rounds sequentially up to ``upto`` while
        contributions exist (never an empty round — ForceAdvanceLocked),
        then release any parked pulls the advance satisfied."""
        while st.closed < upto and st.arrived.get(st.closed + 1):
            st.closed += 1
            st.arrived.pop(st.closed, None)
            st.close_t = t
        release_parked(st, t)

    def on_push_arrived(key: int, worker: int, rnd: int, t: float) -> None:
        st = ks(key)
        v = rnd + 1
        st.arrived.setdefault(v, set()).add(worker)
        # natural close: every live worker contributed, in round order
        while (st.arrived.get(st.closed + 1) is not None
               and live <= st.arrived[st.closed + 1]):
            st.closed += 1
            st.arrived.pop(st.closed, None)
            st.close_t = t
        # the push that just landed may be the contribution that lets a
        # parked fast-worker pull force the ladder forward
        # (ForcePendingLocked)
        if st.parked:
            target = max(force_min(p_task.round + 1)
                         for (_, p_task, _) in st.parked)
            if target > st.closed:
                close_rounds(key, st, target, t)
        release_parked(st, t)

    # --- the server as a resource --------------------------------------------
    # The engine pool's decode_sum/encode loops are MEMORY-BANDWIDTH
    # bound: concurrent slots do not add throughput (measured — the
    # first pull after a push burst waits out the whole decode backlog
    # at the single-thread rate), so the server books work on ONE
    # serialized timeline, exactly like a bucket charge.
    server_free_at = [0.0]
    encode_memo: Dict[Tuple[int, int], float] = {}

    def server_book(t_ready: float, dur_us: float) -> float:
        start = max(t_ready, server_free_at[0])
        end = start + dur_us * 1e-6
        server_free_at[0] = end
        return end

    # --- stage service + completion ------------------------------------------
    def finish_pull(task: _Task, t_served: float) -> None:
        """Round served: the server re-encodes the aggregate (once per
        (key, round) — every worker pulls the same snapshot, server.cc
        caches the re-encode), the response transits the worker's recv
        bucket, and the PULL stage completes."""
        ws = workers[task.worker]
        st = ks(task.key)
        memo_key = (task.key, st.closed)
        t_resp = encode_memo.get(memo_key)
        if t_resp is None:
            t_resp = server_book(t_served, model.server_pull_us(
                cfg.codec, task.length, cfg.two_way))
            encode_memo[memo_key] = t_resp
        t_resp = max(t_resp, t_served)
        nbytes = model.pull_wire_bytes(cfg.codec, task.length, cfg.two_way)
        t_done = ws.recv[task.owner].charge(t_resp, nbytes)
        t_done += model.stage_overhead_us("PULL") * 1e-6 * jit() \
            * speed(task.worker)
        push_event(t_done, "done", task)

    issues: List[Tuple[float, str, int, int, int]] = []

    def issue(si: int, task: _Task, t: float) -> None:
        name, kind, _pool = stages[si]
        issues.append((t, name, task.key, task.round, task.worker))
        ws = workers[task.worker]
        f = speed(task.worker) * jit()
        if kind == "push":
            over = model.stage_overhead_us(name) * 1e-6 * f
            nbytes = model.wire_bytes(cfg.codec, task.length)
            # the ack does NOT wait for the sum (server.cc: pipelined
            # pushes are legal) — the PUSH span ends at wire completion;
            # the apply books separately on the server resource
            t_done = ws.send[task.owner].charge(t + over, nbytes)
            stage_busy_s[name] += t_done - t
            push_event(t_done, "push_done", task)
        elif kind == "pull":
            t_req = t + model.stage_overhead_us("PULL_REQ") * 1e-6 * f
            st = ks(task.key)
            v = task.round + 1
            fm = force_min(v)
            if fm > st.closed:
                close_rounds(task.key, st, fm, t_req)
            if st.closed >= serve_min(v):
                finish_pull(task, max(t_req, st.close_t))
            else:
                st.parked.append((serve_min(v), task, t_req))
        else:
            dur = model.compute_us(name, cfg.codec, task.length) * 1e-6 * f
            stage_busy_s[name] += dur
            push_event(t + dur, "done", task)

    def pump(t: float) -> None:
        while True:
            issued = False
            for w in range(n_workers):
                ws = workers[w]
                for si, (name, kind, pool) in enumerate(stages):
                    if not ws.queues[si] or ws.busy[si] >= pool:
                        continue
                    credited = name in ("COMPRESS", "PUSH")
                    task = pop_ready(ws, si, credited)
                    if task is None:
                        continue
                    if credited and not task.holds_credit:
                        ws.acquire_credit(task)
                    ws.busy[si] += 1
                    issue(si, task, t)
                    issued = True
                    break
                if issued:
                    break
            if not issued:
                return

    # --- main loop -----------------------------------------------------------
    for w in range(n_workers):
        if pipelined:
            for rnd in range(cfg.rounds):
                enqueue_round(w, rnd, 0.0)
        else:
            enqueue_round(w, 0, 0.0)
    pump(0.0)

    while events:
        t, _seq, kind, task = heapq.heappop(events)
        if kind == "apply":
            key, wkr, rnd = task
            on_push_arrived(key, wkr, rnd, t)
            pump(t)
            continue
        ws = workers[task.worker]
        si = task.stage_idx
        if kind == "push_done":
            # apply books on the serialized server resource; the round
            # bookkeeping fires when the decode_sum actually lands
            t_apply = server_book(
                t, model.server_push_us(cfg.codec, task.length))
            push_event(t_apply, "apply",
                       (task.key, task.worker, task.round))
            ws.busy[si] -= 1
            ws.release_credit(task)   # releases_credit: wire-scoped
        else:
            ws.busy[si] -= 1
        if si + 1 < n_stages:
            task.stage_idx = si + 1
            heapq.heappush(ws.queues[si + 1], (task.sort_key, task))
        else:
            # finish: retire round, release any held credit
            ws.release_credit(task)
            if rounds_window is not None:
                rounds = ws.key_rounds.get(task.key)
                if rounds is not None:
                    rounds.discard(task.round)
                    if not rounds:
                        ws.key_rounds.pop(task.key, None)
            ws.round_remaining[task.round] -= 1
            if ws.round_remaining[task.round] == 0:
                ws.round_done_t[task.round] = t
                if not pipelined and ws.round_enqueued < cfg.rounds:
                    enqueue_round(task.worker, ws.round_enqueued, t)
        pump(t)

    # --- results -------------------------------------------------------------
    done_t = [max(ws.round_done_t.get(r, 0.0) for ws in workers)
              for r in range(cfg.rounds)]
    round_times: List[float] = []
    prev = 0.0
    for t in done_t:
        round_times.append(t - prev)
        prev = t
    srt = sorted(round_times)
    mid = len(srt) // 2
    step = (srt[mid] if len(srt) % 2 else 0.5 * (srt[mid - 1] + srt[mid]))
    return SimResult(
        step_time_s=step,
        round_times_s=round_times,
        makespan_s=done_t[-1] if done_t else 0.0,
        tasks=tasks_per_round * cfg.rounds * n_workers,
        config=cfg,
        stage_busy_s={k_: round(v, 6) for k_, v in stage_busy_s.items()},
        issues=issues,
    )
