"""Solve the config space in simulation instead of sweeping it live.

Three consumers (ROADMAP item 3's payoff points):

* :func:`rank_configs` — sweep/hill-climb a ``SimConfig`` grid and
  return the ranked list (``bench.py --mode whatif`` prints the top);
* :func:`make_proposer` — the :class:`~byteps_tpu.common.tuner.AutoTuner`
  ``proposer=`` hook: after the tuner's warmup window it asks the
  simulator for the next candidate instead of walking blind
  coordinate-descent neighbors, and converges the moment the ranked
  list is exhausted (strictly fewer live evaluations than the grid
  walk — pinned in tests/test_sim.py);
* :func:`goodput_estimator` — the
  :class:`~byteps_tpu.common.autoscaler.ScalingPolicy` ``estimator=``
  hook: an admit/evict decision predicts its own payoff (simulated
  per-worker goodput at live±1) before spending capacity.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from byteps_tpu.common.logging import get_logger
from byteps_tpu.sim.engine import SimConfig
from byteps_tpu.sim.extract import (
    CostModel,
    predict_step_s,
    recorded_sim_config,
)

log = get_logger("sim.search")


def rank_configs(
    model: CostModel,
    base: Optional[SimConfig] = None,
    partition_bytes: Optional[Sequence[int]] = None,
    credits: Optional[Sequence[int]] = None,
    codecs: Optional[Sequence[str]] = None,
    staleness: Optional[Sequence[int]] = None,
    throttle_mbps: Optional[Sequence[float]] = None,
    pod_controllers: Optional[Sequence[int]] = None,
    limit: Optional[int] = None,
) -> List[Tuple[SimConfig, float]]:
    """Exhaustive predicted sweep over the cross product of the given
    axes (unspecified axes stay at ``base``); returns
    ``[(SimConfig, predicted_step_s)]`` fastest-first. The whole point
    of the simulator is that a 6-axis product that would take hours of
    wall-clock to measure runs in milliseconds of arithmetic — sweep
    breadth is limited by ``limit`` only for log hygiene."""
    if base is None:
        # the ONE recorded-config -> SimConfig mapping (extract owns it)
        base = recorded_sim_config(model.recorded)
    axes = {
        "partition_bytes": partition_bytes,
        "credit": credits,
        "codec": codecs,
        "staleness": staleness,
        "throttle_mbps": throttle_mbps,
        "pod_controllers": pod_controllers,
    }
    axes = {k: list(v) for k, v in axes.items() if v is not None}
    if not axes:
        return [(base, predict_step_s(model, base))]
    names = list(axes)
    out: List[Tuple[SimConfig, float]] = []
    for combo in itertools.product(*(axes[n] for n in names)):
        cfg = dataclasses.replace(base, **dict(zip(names, combo)))
        out.append((cfg, predict_step_s(model, cfg)))
    out.sort(key=lambda cv: cv[1])
    return out[:limit] if limit else out


def make_proposer(
    model: CostModel,
    base: Optional[SimConfig] = None,
    partition_grid: Optional[Sequence[int]] = None,
    credit_grid: Optional[Sequence[int]] = None,
    top_n: int = 4,
) -> Callable[[Tuple[int, int], Optional[float], Dict[Tuple[int, int],
                                                      float]],
              Optional[Tuple[int, int]]]:
    """Build an :class:`~byteps_tpu.common.tuner.AutoTuner` ``proposer``:
    rank the (partition_bytes, credit) grid in simulation ONCE, then
    hand the tuner the predicted-fastest candidates it has not yet
    measured, best first. Returning ``None`` (list exhausted) converges
    the tuner on its measured best — the live evaluations are spent
    CONFIRMING the simulator's shortlist, not exploring neighbors."""
    from byteps_tpu.common.tuner import CREDIT_GRID, PARTITION_GRID

    pgrid = list(partition_grid if partition_grid is not None
                 else PARTITION_GRID)
    cgrid = list(credit_grid if credit_grid is not None else CREDIT_GRID)
    ranked = rank_configs(model, base=base, partition_bytes=pgrid,
                          credits=cgrid)
    shortlist: List[Tuple[int, int]] = [
        (cfg.partition_bytes, cfg.credit) for cfg, _ in ranked[:top_n]]
    log.info("sim proposer: shortlist %s (of %d simulated)",
             [(pb >> 10, cr) for pb, cr in shortlist], len(ranked))

    def proposer(current, best_time, measured):
        for cand in shortlist:
            if cand not in measured:
                return cand
        return None

    return proposer


def goodput_estimator(
    model: CostModel,
    base: Optional[SimConfig] = None,
    rounds: int = 3,
) -> Callable[[int], float]:
    """Build a :class:`~byteps_tpu.common.autoscaler.ScalingPolicy`
    ``estimator``: ``estimator(n_workers) -> predicted aggregate
    goodput`` (rounds/s × workers, i.e. useful work per wall-second).
    An admit is worth its capacity only when goodput(live+1) beats
    goodput(live) — round-close barriers and server contention make
    that genuinely sublinear, which is exactly what the replay engine
    models. Memoized: the policy calls it at live and live±1 every
    decision."""
    if base is None:
        base = recorded_sim_config(model.recorded, rounds=rounds)
    cache: Dict[int, float] = {}

    def estimator(n_workers: int) -> float:
        n = max(1, int(n_workers))
        if n not in cache:
            cfg = dataclasses.replace(base, num_workers=n, rounds=rounds)
            step = predict_step_s(model, cfg)
            cache[n] = n / step if step > 0 else 0.0
        return cache[n]

    return estimator
