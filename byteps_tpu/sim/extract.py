"""Lift one recorded run into a calibrated cost model.

Inputs are exactly what the instrumentation already produces (and, since
this PR, stamps with the run's resolved ``Config.snapshot()`` so a
recorded run is replayable without out-of-band knowledge of the knobs
that produced it):

* the chrome trace (``BYTEPS_TRACE_ON=1``) — per-stage spans carrying
  ``args.key`` / ``args.length``, from which we take per-stage
  service-time fits and the tensor/partition structure;
* the flight recorder's per-step ring (degraded input: per-stage run
  percentiles, no per-partition detail — ``cost_model_from_flight_dump``);
* the run's resolved config (trace metadata ``config`` row, or passed
  explicitly).

Three calibration passes, all deterministic once done:

1. **service-time fits** — per stage, ``a_us + b_us_per_byte × dense
   bytes`` least-squares over the recorded spans (single-partition-size
   runs borrow the slope from the codec table and keep the measured
   intercept);
2. **codec table** — encode/decode µs/byte for every wire codec,
   micro-measured on this host at extract time (the recorded run only
   exercised ONE codec; what-ifs over the others need their compute
   cost, and bytes-on-wire ratios are closed-form via
   ``compression/wire.py``);
3. **round slack** — replay the RECORDED config in the simulator and
   book the residual vs the measured step time as a per-round constant
   (handle assembly, enqueue overhead — everything outside the staged
   pipeline). Self-replay of the recorded config is then ~exact by
   construction, and the constant transfers across what-ifs.

See docs/whatif.md for the full list of modeling assumptions.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.partition import MAX_PARTS_PER_TENSOR
from byteps_tpu.compression import wire as wire_mod
from byteps_tpu.compression.wire import WireCodec

log = get_logger("sim.extract")

# Default loopback "wire" rate when the recorded run was unthrottled and
# the spans don't pin one (bytes cross a localhost socket at memcpy-ish
# speed; the exact figure only matters for unthrottled what-ifs).
_DEFAULT_LOOPBACK_BPS = 4e9

# stage-name fallbacks (µs) when the recorded trace never exercised a
# stage — deliberately small: unknown ≠ expensive
_DEFAULT_OVERHEAD_US = {"PUSH": 150.0, "PULL": 150.0, "PULL_REQ": 50.0}


def codec_by_name(name: str) -> Optional[WireCodec]:
    """The bench-canonical wire-codec instances (bench.py --mode
    throttled races exactly these constructions)."""
    if name in (None, "", "raw", "none"):
        return None
    if name == "fp16":
        return wire_mod.Fp16Wire()
    if name == "fp8":
        return wire_mod.Fp8Wire()
    if name == "onebit":
        return wire_mod.OnebitWire(scaling=True)
    if name == "topk":
        return wire_mod.TopkWire(k=0.01, selection="block")
    if name == "randomk":
        return wire_mod.RandomkWire(k=0.01)
    if name == "dither":
        return wire_mod.DitherWire()
    raise ValueError(f"unknown wire codec {name!r}")


def calibrate_codecs(names: Sequence[str] = ("raw", "fp16", "fp8",
                                             "onebit", "topk"),
                     nbytes: int = 4 << 20, reps: int = 2,
                     ) -> Dict[str, Dict[str, float]]:
    """Micro-measure encode/decode µs per dense byte for each codec on
    THIS host. The recorded run only exercised one codec; a what-if over
    another needs its compute cost from somewhere, and the codecs are
    pure numpy — a 4 MB sample at ``reps`` reps costs milliseconds.
    min-of-reps: codec arithmetic has no long tail, the min is the
    honest per-byte rate."""
    n = max(1, nbytes // 4)
    x = np.random.default_rng(0).standard_normal(n).astype(np.float32)
    table: Dict[str, Dict[str, float]] = {}
    # the summation server's fp32 accumulate (reduce_sum_f32 is SIMD C;
    # numpy's += is the same memory-bound operation) — priced once,
    # applied per push on the server model
    acc = np.zeros_like(x)
    sums = []
    for _ in range(reps + 1):
        t0 = time.perf_counter()
        acc += x
        sums.append(time.perf_counter() - t0)
    table["_sum"] = {"us_per_byte": min(sums[1:]) * 1e6 / (n * 4)}
    lib = _codec_lib()
    for name in names:
        codec = codec_by_name(name)
        enc_ts, dec_ts = [], []
        for _ in range(reps + 1):   # rep 0 = warmup (imports, caches)
            t0 = time.perf_counter()
            buf = (codec.encode(x, 0) if codec is not None
                   else x.view(np.uint8).ravel())
            t1 = time.perf_counter()
            if codec is not None:
                codec.decode(buf, n, 0)
            else:
                np.ascontiguousarray(buf).view(np.float32).copy()
            t2 = time.perf_counter()
            enc_ts.append(t1 - t0)
            dec_ts.append(t2 - t1)
        table[name] = {
            "encode_us_per_byte": min(enc_ts[1:]) * 1e6 / (n * 4),
            "decode_us_per_byte": min(dec_ts[1:]) * 1e6 / (n * 4),
        }
        if lib is not None:
            table[name].update(_server_codec_rates(lib, codec, x, buf,
                                                   reps))
    return table


def _codec_lib():
    """The native server library's codec-calibration surface, or None on
    an analysis-only box (no compiler / no native build) — the numpy
    rates then stand in for the server loops."""
    try:
        from byteps_tpu.server.native import load_lib

        lib = load_lib()
        lib.bps_codec_encode  # noqa: B018 — staleness probe
        return lib
    except Exception as e:  # noqa: BLE001 — calibration must degrade
        log.info("sim.extract: native codec calibration unavailable "
                 "(%s); using host-numpy rates for the server model", e)
        return None


def _server_codec_rates(lib, codec: Optional[WireCodec], x: np.ndarray,
                        payload: np.ndarray, reps: int,
                        ) -> Dict[str, float]:
    """Price the server's REAL C++ loops per dense byte: ``decode_sum``
    (push apply — decode + fp32 accumulate in one pass) and ``encode``
    (the two-way pull re-encode). These are NOT the numpy rates: onebit's
    unpack and topk's reselection differ by integer factors between the
    two implementations, and the server's side of a what-if leg must be
    priced with the server's own code."""
    n = x.size
    cid = codec.codec_id if codec is not None else 0
    payload = np.ascontiguousarray(payload)
    dst = np.zeros(n, np.float32)
    topk_k = 0
    if codec is not None and isinstance(codec, wire_mod.TopkWire):
        topk_k = int(payload[:4].view(np.uint32)[0])
    cap = int(max(payload.nbytes, n * 4) + 16)
    out = np.empty(cap, np.uint8)
    dec_ts, enc_ts = [], []
    for _ in range(reps + 1):
        t0 = time.perf_counter()
        rc = lib.bps_codec_decode_sum(cid, payload.ctypes.data,
                                      payload.nbytes, dst.ctypes.data, n)
        t1 = time.perf_counter()
        sz = lib.bps_codec_encode(cid, x.ctypes.data, n, topk_k, 0,
                                  out.ctypes.data, cap)
        t2 = time.perf_counter()
        if rc != 0 or sz <= 0:
            log.warning("sim.extract: native codec %d calibration "
                        "failed (rc=%s, sz=%s)", cid, rc, sz)
            return {}
        dec_ts.append(t1 - t0)
        enc_ts.append(t2 - t1)
    return {
        "sdecode_us_per_byte": min(dec_ts[1:]) * 1e6 / (n * 4),
        "sencode_us_per_byte": min(enc_ts[1:]) * 1e6 / (n * 4),
    }


def _fit_linear(samples: List[Tuple[float, float]],
                fallback_slope: float = 0.0,
                ) -> Tuple[float, float]:
    """(a_us, b_us_per_byte) for ``dur_us ≈ a + b·bytes``. One distinct
    size can't pin a slope — borrow ``fallback_slope`` and keep the
    measured intercept."""
    if not samples:
        return 0.0, fallback_slope
    sizes = {s for s, _ in samples}
    med = statistics.median(d for _, d in samples)
    if len(sizes) < 2:
        b = fallback_slope
        a = max(0.0, med - b * next(iter(sizes)))
        return a, b
    xs = np.array([s for s, _ in samples], dtype=np.float64)
    ys = np.array([d for _, d in samples], dtype=np.float64)
    A = np.stack([np.ones_like(xs), xs], axis=1)
    (a, b), *_ = np.linalg.lstsq(A, ys, rcond=None)
    return max(0.0, float(a)), max(0.0, float(b))


@dataclasses.dataclass
class CostModel:
    """Everything :func:`byteps_tpu.sim.engine.simulate` needs, as plain
    data (``to_dict``/``from_dict`` round-trips it — the
    ``--whatif-export`` payload)."""

    pipeline: str                              # "dcn" | "hybrid"
    # (tensor_id, name, num_elements) rows, declaration order
    tensors: List[Tuple[int, str, int]]
    # stage -> (a_us, b_us_per_byte) over DENSE bytes
    stage_fits: Dict[str, Tuple[float, float]]
    # stage -> fixed per-task overhead µs (wire stages)
    overheads: Dict[str, float]
    # codec name -> encode/decode µs per dense byte
    codec_table: Dict[str, Dict[str, float]]
    recorded: Dict[str, Any]                   # the run's resolved config
    loopback_bps: float = _DEFAULT_LOOPBACK_BPS
    min_compress_bytes: int = 65536
    round_slack_us: float = 0.0                # see module docstring
    _codec_cache: Dict[str, Optional[WireCodec]] = dataclasses.field(
        default_factory=dict, repr=False, compare=False)

    # -- structure ------------------------------------------------------------
    def partition_layout(self, partition_bytes: int,
                         ) -> List[Tuple[int, int, int, int]]:
        """(key, part_idx, length, priority) rows under a hypothetical
        partition size — the same arithmetic as
        ``common/partition.make_partitions`` (fp32 itemsize)."""
        plen = max(1, int(partition_bytes) // 4)
        rows = []
        for (tid, _name, nelems) in self.tensors:
            n_parts = max(1, -(-nelems // plen))
            for i in range(n_parts):
                off = i * plen
                rows.append((tid * MAX_PARTS_PER_TENSOR + i, i,
                             min(plen, nelems - off), -tid))
        return rows

    # -- codecs ---------------------------------------------------------------
    def _codec(self, name: str, length: int) -> Optional[WireCodec]:
        """Partition-effective codec: below BYTEPS_MIN_COMPRESS_BYTES
        every partition rides raw, matching the live pipelines."""
        if length * 4 < self.min_compress_bytes:
            return None
        if name not in self._codec_cache:
            self._codec_cache[name] = codec_by_name(name)
        return self._codec_cache[name]

    def wire_bytes(self, codec: str, length: int) -> int:
        c = self._codec(codec, length)
        return c.wire_bytes(length) if c is not None else length * 4

    def pull_wire_bytes(self, codec: str, length: int,
                        two_way: bool) -> int:
        c = self._codec(codec, length)
        if c is None:
            return length * 4
        compacted = type(c).store_elems is not WireCodec.store_elems
        if compacted:
            return c.store_elems(length) * 4
        return c.wire_bytes(length) if two_way else length * 4

    # -- rates ----------------------------------------------------------------
    def wire_rate_bps(self, throttle_mbps: float) -> float:
        if throttle_mbps and throttle_mbps > 0:
            return float(throttle_mbps) * 1e6 / 8.0
        return self.loopback_bps

    # -- service times --------------------------------------------------------
    def stage_overhead_us(self, name: str) -> float:
        return self.overheads.get(name,
                                  _DEFAULT_OVERHEAD_US.get(name, 0.0))

    def _codec_rate(self, codec: str, op: str) -> float:
        row = self.codec_table.get(codec)
        if row is None:
            row = self.codec_table.get("raw", {})
        return float(row.get(f"{op}_us_per_byte", 0.0))

    def server_push_us(self, codec: str, length: int) -> float:
        """Server-side cost of applying one push: ``decode_sum`` — the
        codec decode + fp32 accumulate in one pass. Priced by the
        native-calibrated ``sdecode`` rate (the server's own C++ loop);
        falls back to host-numpy decode + sum rates on an analysis-only
        box."""
        dense = length * 4
        eff = codec if self._codec(codec, length) is not None else "raw"
        row = self.codec_table.get(eff, {})
        if "sdecode_us_per_byte" in row:
            return float(row["sdecode_us_per_byte"]) * dense
        sum_us = self.codec_table.get("_sum", {}).get(
            "us_per_byte", 0.0) * dense
        if eff == "raw":
            return sum_us
        return sum_us + self._codec_rate(eff, "decode") * dense

    def server_pull_us(self, codec: str, length: int,
                       two_way: bool) -> float:
        """Server-side cost of preparing one pull response: re-encoding
        the aggregate for two-way codecs (a raw / one-way response is a
        memcpy, absorbed by the PULL overhead)."""
        c = self._codec(codec, length)
        if c is None or not two_way:
            return 0.0
        if type(c).store_elems is not WireCodec.store_elems:
            return 0.0  # compacted store: the store IS the response
        row = self.codec_table.get(codec, {})
        if "sencode_us_per_byte" in row:
            return float(row["sencode_us_per_byte"]) * length * 4
        return self._codec_rate(codec, "encode") * length * 4

    def compute_us(self, stage: str, codec: str, length: int) -> float:
        """Service time of a non-wire stage for one partition. COMPRESS/
        DECOMPRESS are codec-aware: the recorded codec keeps its measured
        fit, every other codec prices via the micro-calibrated table
        (plus the recorded stage intercept — dispatch cost is
        codec-independent)."""
        dense = length * 4
        eff = codec if self._codec(codec, length) is not None else "raw"
        if stage in ("COMPRESS", "DECOMPRESS"):
            op = "encode" if stage == "COMPRESS" else "decode"
            a, b = self.stage_fits.get(stage, (0.0, 0.0))
            if eff == self.recorded.get("codec", "raw") and \
                    stage in self.stage_fits:
                return a + b * dense
            return a + self._codec_rate(eff, op) * dense
        a, b = self.stage_fits.get(stage, (0.0, 0.0))
        return a + b * dense

    # -- serialization --------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "pipeline": self.pipeline,
            "tensors": [list(t) for t in self.tensors],
            "stage_fits": {k: list(v) for k, v in self.stage_fits.items()},
            "overheads": dict(self.overheads),
            "codec_table": self.codec_table,
            "recorded": self.recorded,
            "loopback_bps": self.loopback_bps,
            "min_compress_bytes": self.min_compress_bytes,
            "round_slack_us": self.round_slack_us,
        }

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "CostModel":
        return cls(
            pipeline=doc["pipeline"],
            tensors=[tuple(t) for t in doc["tensors"]],
            stage_fits={k: tuple(v)
                        for k, v in doc["stage_fits"].items()},
            overheads=dict(doc["overheads"]),
            codec_table=doc["codec_table"],
            recorded=doc["recorded"],
            loopback_bps=float(doc.get("loopback_bps",
                                       _DEFAULT_LOOPBACK_BPS)),
            min_compress_bytes=int(doc.get("min_compress_bytes", 65536)),
            round_slack_us=float(doc.get("round_slack_us", 0.0)),
        )


def recorded_sim_config(recorded: Dict[str, Any], rounds: int = 3):
    """The ONE recorded-config → :class:`SimConfig` mapping (self-replay,
    `rank_configs`' default base, and the goodput estimator all route
    here — a knob added to SimConfig is threaded once)."""
    from byteps_tpu.sim.engine import SimConfig

    return SimConfig(
        partition_bytes=int(recorded.get("partition_bytes", 4096000)),
        credit=int(recorded.get("scheduling_credit",
                                recorded.get("credit", 4))),
        codec=str(recorded.get("codec", "raw")),
        throttle_mbps=float(recorded.get("dcn_throttle_mbps",
                                         recorded.get("throttle_mbps",
                                                      0.0))),
        staleness=int(recorded.get("staleness", 0)),
        pod_controllers=int(recorded.get("pod_controllers", 1)),
        owner_salt=int(recorded.get("owner_salt", 0)),
        num_workers=int(recorded.get("num_worker", 1)),
        rounds=rounds,
    )


def predict_step_s(model: CostModel, cfg) -> float:
    """Simulated median step time + the calibrated per-round slack —
    THE number ``bench.py --mode whatif`` tables against measurement."""
    from byteps_tpu.sim.engine import simulate

    return simulate(model, cfg).step_time_s + model.round_slack_us * 1e-6


def cost_model_from_events(
    events: Sequence[Dict[str, Any]],
    config: Optional[Dict[str, Any]] = None,
    measured_step_s: Optional[float] = None,
    codec_table: Optional[Dict[str, Dict[str, float]]] = None,
) -> CostModel:
    """Extract a :class:`CostModel` from chrome-trace events.

    ``config`` defaults to the trace metadata's stamped
    ``Config.snapshot()`` (pass ``load_trace_doc`` output, or merge it
    yourself). ``measured_step_s`` — the recorded leg's measured median
    round time — calibrates the round slack; without it the slack is
    fit against the trace's own per-round makespans (which exclude the
    caller's assemble/enqueue gap).
    """
    from byteps_tpu.common.trace_analysis import (
        partition_lifecycles,
        step_makespans,
    )

    config = dict(config or {})
    recorded_codec = str(config.get("codec", "raw"))
    recorded_rate = float(config.get("dcn_throttle_mbps", 0.0))

    # per-stage samples (dense bytes, dur_us) + tensor structure, both
    # straight from the spans
    lifecycles = partition_lifecycles(events)
    pipeline = "dcn"
    stage_samples: Dict[str, List[Tuple[float, float]]] = {}
    tensor_elems: Dict[str, int] = {}
    tensor_keys: Dict[str, int] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        tid = str(e.get("tid"))
        args = e.get("args", {}) or {}
        length = args.get("length")
        if length is None:
            continue
        if tid in ("REDUCE", "COPYD2H", "COPYH2D", "ALLGATHER"):
            pipeline = "hybrid"
        stage_samples.setdefault(tid, []).append(
            (float(length) * 4.0, float(e.get("dur", 0.0))))
        key = args.get("key")
        if key is not None:
            name = str(e.get("name", "")).rsplit(".p", 1)[0]
            tensor_keys[name] = int(key) // MAX_PARTS_PER_TENSOR
    # total elements per tensor = sum of round-0 partition lengths
    for lc in lifecycles:
        if lc["round"] != 0 or lc.get("length") is None:
            continue
        name = str(lc["name"]).rsplit(".p", 1)[0]
        tensor_elems[name] = tensor_elems.get(name, 0) + int(lc["length"])
    tensors = sorted(
        (tensor_keys.get(name, i), name, n)
        for i, (name, n) in enumerate(tensor_elems.items()))
    if not tensors:
        raise ValueError("trace has no partition spans with args.length "
                         "— was BYTEPS_TRACE_ON armed over the window?")

    table = codec_table if codec_table is not None else calibrate_codecs()

    # codec-stage fits borrow the table's slope when the run used one
    # partition size (the usual case)
    fits: Dict[str, Tuple[float, float]] = {}
    for st, samples in stage_samples.items():
        if st in ("PUSH", "PULL"):
            continue
        slope = 0.0
        if st == "COMPRESS":
            slope = float(table.get(recorded_codec, {}).get(
                "encode_us_per_byte", 0.0))
        elif st == "DECOMPRESS":
            slope = float(table.get(recorded_codec, {}).get(
                "decode_us_per_byte", 0.0))
        fits[st] = _fit_linear(samples, fallback_slope=slope)

    codec_obj = codec_by_name(recorded_codec)
    min_cb = int(config.get("min_compress_bytes", 65536))
    loopback = _DEFAULT_LOOPBACK_BPS
    if recorded_rate <= 0 and "PUSH" in stage_samples:
        # unthrottled recorded run: the push spans THEMSELVES pin the
        # loopback rate (bytes / median span time)
        med = statistics.median(d for _, d in stage_samples["PUSH"])
        dense = statistics.median(s for s, _ in stage_samples["PUSH"])
        nbytes = (codec_obj.wire_bytes(int(dense // 4)) if codec_obj
                  else dense)
        if med > 0:
            loopback = max(1e6, nbytes / (med * 1e-6))
    rate = (recorded_rate * 1e6 / 8.0 if recorded_rate > 0 else loopback)

    # wire-stage overheads: per-span residual after subtracting the two
    # MODELED components the span carries — own-bytes transmission at
    # the recorded rate and the server's decode/sum (push) or re-encode
    # (pull) for the recorded codec. Later spans' durs also carry
    # sibling token-bucket debt, which the sim reproduces — so the p25
    # of the residuals (≈ the freshest-bucket spans) is the honest
    # per-op overhead, not the median.
    rec_row = table.get(recorded_codec, {})
    enc_rate = float(rec_row.get("sencode_us_per_byte",
                                 rec_row.get("encode_us_per_byte", 0.0)))
    overheads: Dict[str, float] = {}
    for st in ("PUSH", "PULL"):
        xs = [e for e in events
              if e.get("ph") == "X" and e.get("tid") == st
              and (e.get("args") or {}).get("length") is not None]
        resid = []
        for e in xs:
            length = int(e["args"]["length"])
            use_codec = (codec_obj if length * 4 >= min_cb else None)
            dense = length * 4
            if st == "PUSH":
                # the ack does not wait for the sum — a push span is
                # wire time + framing only
                nbytes = (use_codec.wire_bytes(length) if use_codec
                          else dense)
                server_us = 0.0
            else:
                if use_codec is None:
                    nbytes = dense
                    server_us = 0.0
                else:
                    compacted = (type(use_codec).store_elems
                                 is not WireCodec.store_elems)
                    nbytes = (use_codec.store_elems(length) * 4 if compacted
                              else use_codec.wire_bytes(length))
                    server_us = 0.0 if compacted else enc_rate * dense
            r = float(e["dur"]) - nbytes / rate * 1e6 - server_us
            resid.append(max(0.0, r))
        # the MIN residual is the freshest-bucket span (overheads can't
        # be negative, so anything the min still carries is genuine
        # fixed cost); every later span also carries sibling bucket
        # debt, which the sim reproduces — calibrating on a median
        # would double-count a whole transmission
        overheads[st] = min(resid) if resid else _DEFAULT_OVERHEAD_US[st]

    model = CostModel(
        pipeline=pipeline,
        tensors=tensors,
        stage_fits=fits,
        overheads=overheads,
        codec_table=table,
        recorded={
            "codec": recorded_codec,
            "partition_bytes": int(config.get("partition_bytes", 4096000)),
            "scheduling_credit": int(config.get("scheduling_credit", 4)),
            "dcn_throttle_mbps": recorded_rate,
            "staleness": int(config.get("staleness", 0)),
            "pod_controllers": int(config.get("pod_controllers", 1)),
            "owner_salt": int(config.get("owner_salt", 0)),
            "num_worker": int(config.get("num_worker", 1)),
        },
        loopback_bps=loopback,
        min_compress_bytes=min_cb,
    )

    # round-slack calibration: self-replay the recorded config and book
    # the residual vs the measured step time as a per-round constant
    makespans = step_makespans(lifecycles)
    rounds = max(1, len(makespans))
    recorded_step_s = measured_step_s
    if recorded_step_s is None and makespans:
        recorded_step_s = statistics.median(
            m["makespan_us"] for m in makespans) * 1e-6
    if recorded_step_s:
        from byteps_tpu.sim.engine import simulate

        sim = simulate(model, recorded_sim_config(
            model.recorded, rounds=min(3, rounds)))
        model.round_slack_us = (recorded_step_s - sim.step_time_s) * 1e6
        log.info("sim.extract: self-replay %.1fms vs recorded %.1fms "
                 "-> round slack %.1fus",
                 sim.step_time_s * 1e3, recorded_step_s * 1e3,
                 model.round_slack_us)
    return model


def cost_model_from_flight_dump(
    doc: Dict[str, Any],
    config: Optional[Dict[str, Any]] = None,
    codec_table: Optional[Dict[str, Dict[str, float]]] = None,
) -> CostModel:
    """DEGRADED extraction from a flight-recorder post-mortem dump: the
    per-step ring has per-stage run p50s but no per-partition spans, so
    stage costs are flat fits, the payload size comes from the wire
    counters (bytes pushed / steps seen), and the round slack from the
    ring's own ``step_ms``. Good enough to rank configs; the chrome
    trace is the first-class input."""
    config = dict(config or doc.get("config") or {})
    steps = [s for s in doc.get("steps", []) if s.get("stages")]
    if not steps:
        raise ValueError("flight dump has no per-step stage snapshots "
                         "(BYTEPS_FLIGHT_RECORDER_STEPS=0?)")
    counters = (doc.get("metrics", {}).get("counters", {})
                or steps[-1].get("counters", {}))
    pushed = float(counters.get("wire.push_bytes", 0.0))
    # wire.push_bytes is cumulative over the WHOLE run while the ring is
    # bounded — divide by the absolute step span the counters cover, not
    # the ring length (a long run's post-mortem keeps only the tail)
    last_step = steps[-1].get("step")
    n_steps = max(1, int(last_step) if last_step else len(steps))
    round_bytes = pushed / n_steps if pushed else 4096000.0
    recorded_codec = str(config.get("codec", "raw"))
    codec_obj = codec_by_name(recorded_codec)
    if codec_obj is not None and pushed:
        # wire counters saw ENCODED bytes; invert the codec's ratio at
        # the recorded partition size to recover dense bytes
        plen = max(1, int(config.get("partition_bytes", 4096000)) // 4)
        ratio = codec_obj.wire_bytes(plen) / (plen * 4.0)
        round_bytes /= max(ratio, 1e-9)
    nelems = max(1, int(round_bytes // 4))

    fits: Dict[str, Tuple[float, float]] = {}
    pipeline = "dcn"
    for st in steps[-1]["stages"]:
        if st in ("REDUCE", "COPYD2H", "COPYH2D", "ALLGATHER"):
            pipeline = "hybrid"
        p50s = [s["stages"][st].get("run_p50_us") for s in steps
                if st in s.get("stages", {})]
        p50s = [p for p in p50s if p]
        if p50s and st not in ("PUSH", "PULL"):
            fits[st] = (float(statistics.median(p50s)), 0.0)
    step_ms = [s.get("step_ms") for s in steps if s.get("step_ms")]
    table = codec_table if codec_table is not None else calibrate_codecs()
    model = CostModel(
        pipeline=pipeline,
        tensors=[(0, "flight", nelems)],
        stage_fits=fits,
        overheads={},
        codec_table=table,
        recorded={
            "codec": recorded_codec,
            "partition_bytes": int(config.get("partition_bytes", 4096000)),
            "scheduling_credit": int(config.get("scheduling_credit", 4)),
            "dcn_throttle_mbps": float(config.get("dcn_throttle_mbps",
                                                  0.0)),
            "staleness": int(config.get("staleness", 0)),
            "pod_controllers": int(config.get("pod_controllers", 1)),
            "owner_salt": int(config.get("owner_salt", 0)),
            "num_worker": int(config.get("num_worker", 1)),
        },
        min_compress_bytes=int(config.get("min_compress_bytes", 65536)),
    )
    if step_ms:
        from byteps_tpu.sim.engine import simulate

        sim = simulate(model, recorded_sim_config(model.recorded, 3))
        model.round_slack_us = (
            statistics.median(step_ms) * 1e3 - sim.step_time_s * 1e6)
    return model
