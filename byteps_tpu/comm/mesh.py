"""Device-mesh helpers.

The reference coordinates GPU ranks through env vars + unix sockets
(``communicator.cc``); on TPU the single-controller model makes the local
"rank table" just a ``jax.sharding.Mesh``. Multi-host rendezvous is
``jax.distributed`` (reference: ps-lite scheduler rendezvous, SURVEY §5.8).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

from byteps_tpu.common.config import get_config


def local_device_count() -> int:
    return jax.local_device_count()


def device_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Tuple[str, ...]] = None,
) -> Mesh:
    """Build a mesh; default is 1-D over all devices on the dp axis."""
    cfg = get_config()
    if shape is None:
        shape = (len(jax.devices()),)
    if axis_names is None:
        axis_names = (cfg.dp_axis,) if len(shape) == 1 else tuple(
            f"ax{i}" for i in range(len(shape))
        )
    return jax.make_mesh(tuple(shape), tuple(axis_names))
