"""ICI-tier collectives: the TPU replacement for the reference's NCCL +
PS data plane on the intra-pod path (SURVEY §5.8).

Layout convention for the "eager" (per-tensor push_pull) path: a gradient
set lives as an array of shape ``(N, L)`` sharded over the mesh's dp axis on
axis 0 — row d is device d's local gradient, the analog of one reference
worker-process's GPU buffer. Collectives run inside ``shard_map`` and return
a replicated ``(L,)`` result.

The compressed all-reduce reproduces the reference's hybrid-PS dataflow
(worker compress → server decompress → fp32 sum → server recompress →
worker decompress; ``core_loops.cc`` COMPRESS/PUSH/PULL/DECOMPRESS stages +
``server.cc`` ``SumRecvBuff``) with devices as both workers and "servers":
device j owns segment j of every chunk (the analog of key→server hashing),
receives peers' compressed segments, decompresses, sums in fp32,
recompresses, and broadcasts the result. Wire bytes per direction are
(N−1)/N · compressed_size — the same ratio the reference's
colocated-server topology achieves.

Compressors whose payloads sum positionally (seed-synced randomk) skip the
decompress/recompress round trip entirely — the positional-sum fast path.

Wire tiers (``BYTEPS_ICI_TIER``, per-call ``tier=`` override;
docs/architecture.md three-tier table):

* ``staged`` (default) — payload transport is one monolithic
  ``all_to_all`` ("push") and one ``all_gather`` ("pull"): codec compute
  and wire time serialize and every hop pays the full-exchange latency.
* ``ring`` — the ``ici-compressed`` tier: the same payloads ride ``n−1``
  ring hops (``ops/ring_collective_kernels.py`` — Pallas
  ``make_async_remote_copy`` kernels on TPU, ``lax.ppermute`` twins
  everywhere else), one segment-payload per link per hop, each hop's DMA
  overlapping the neighboring hops' codec work. The aggregation
  arithmetic (worker-ordered payload stack → the codec's own
  ``decompress_sum`` / the shared positional fold → ``two_way``
  recompress) is the SAME expression as the staged path, which is what
  pins the ring result BIT-exact against staged for deterministic codecs
  — EF and two_way included (tests/test_ring_ici.py). Stochastic
  presummable codecs (randomk) instead take the genuinely fused per-hop
  form — ``ring_presum`` accumulates the running partial in payload
  space at every hop, the bandwidth-optimal ring reduce-scatter — whose
  chain-order fp32 adds are pinned statistically (same key schedule and
  support; values at summation-order roundoff).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.common.config import get_config
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.compression.base import Compressor
from byteps_tpu.ops.ring_collective_kernels import (
    ring_allgather,
    ring_collect,
    ring_presum,
)

_TIERS = ("staged", "ring")


def _resolve_tier(tier: Optional[str]) -> str:
    t = tier or get_config().ici_tier
    if t not in _TIERS:
        raise ValueError(
            f"unknown ICI tier {t!r} (BYTEPS_ICI_TIER / tier=): "
            f"expected one of {_TIERS}")
    return t


# handle cache keyed by registry identity (tests reset the registry):
# the dispatch path must not pay a name format + registry lookup per
# collective — the metrics design rule is handles resolved once
_counter_cache = {"reg": None, "counters": {}}


def _ici_counter(name: str):
    reg = get_registry()
    if _counter_cache["reg"] is not reg:
        _counter_cache["reg"] = reg
        _counter_cache["counters"] = {}
    c = _counter_cache["counters"].get(name)
    if c is None:
        c = reg.counter(name)
        _counter_cache["counters"][name] = c
    return c


def _count_dispatch(kind: str) -> None:
    """Always-on ICI collective DISPATCH counter (host-side issue, not
    device completion — the quantity the ici_lock serializes and a stall
    report wants: did the host stop issuing, or did the device stop
    finishing?). One registry counter per collective family."""
    _ici_counter(f"ici.{kind}_dispatch").inc()


def _account_wire(wire_bytes: int, logical_bytes: int) -> None:
    """Per-dispatch ICI wire accounting (always-on, host-side):
    ``ici.wire_bytes`` is what actually crosses the wire PER DEVICE for
    this dispatch (compressed payload bytes, from the payload tree's
    nbytes), ``ici.logical_bytes`` the uncompressed fp32 bytes the same
    collective would move — so the achieved compression / bus-bandwidth
    ratio is computable straight from ``metrics_snapshot()`` and rides
    every flight-recorder step. Scope: the HOST-dispatched collectives
    (the flat wrappers — eager path, hybrid REDUCE/ALLGATHER, bench);
    the fused in-jit paths call the *_local bodies inside one traced
    step and never cross the host per collective, so their traffic is
    not counted here (it is derivable from the chunk count × payload
    nbytes if needed)."""
    if wire_bytes:
        _ici_counter("ici.wire_bytes").inc(int(wire_bytes))
    if logical_bytes:
        _ici_counter("ici.logical_bytes").inc(int(logical_bytes))


_payload_nbytes_cache = {}


def _payload_nbytes(compressor: Compressor, seg: int) -> int:
    """Wire bytes of one compressed segment payload — the actual payload
    tree's nbytes (abstract eval, no compute), not the codec's
    ``compressed_bytes`` estimate."""
    key = (compressor, seg)
    v = _payload_nbytes_cache.get(key)
    if v is None:
        try:
            tree = jax.eval_shape(
                lambda x, k: compressor.compress(x, k),
                jax.ShapeDtypeStruct((seg,), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32),
            )
            v = sum(
                int(functools.reduce(lambda a, b: a * b, l.shape, 1))
                * jnp.dtype(l.dtype).itemsize
                for l in jax.tree.leaves(tree)
            )
        except Exception:  # noqa: BLE001 — accounting must never fail a step
            v = compressor.compressed_bytes(seg)
        _payload_nbytes_cache[key] = v
    return v


def _segment(g: jnp.ndarray, n_dev: int):
    """Pad a flat (L,) vector and view as (n_dev, seg) owner-major segments."""
    L = g.shape[0]
    seg = -(-L // n_dev)
    gp = jnp.pad(g, (0, seg * n_dev - L))
    return gp.reshape(n_dev, seg), seg


@functools.partial(jax.jit, static_argnames=("axis", "average", "mesh"))
def _allreduce_impl(x, *, mesh: Mesh, axis: str, average: bool):
    n = mesh.shape[axis]

    def inner(blk):
        s = jax.lax.psum(blk[0], axis)
        return s / n if average else s

    return jax.shard_map(inner, mesh=mesh, in_specs=P(axis), out_specs=P())(x)


def allreduce_flat(
    x: jnp.ndarray, mesh: Mesh, axis: Optional[str] = None, average: bool = True
) -> jnp.ndarray:
    """Uncompressed all-reduce of (N, L) → (L,): one fused psum."""
    axis = axis or mesh.axis_names[0]
    _count_dispatch("allreduce")
    n = mesh.shape[axis]
    raw = 2 * (n - 1) * (-(-x.shape[1] // n)) * jnp.dtype(x.dtype).itemsize
    _account_wire(raw, raw)
    return _allreduce_impl(x, mesh=mesh, axis=axis, average=average)


@functools.partial(jax.jit, static_argnames=("axis", "mesh"))
def _reduce_scatter_impl(x, *, mesh: Mesh, axis: str):
    n = mesh.shape[axis]
    L = x.shape[1]
    seg = -(-L // n)

    def inner(blk):
        gp = jnp.pad(blk[0], (0, seg * n - L))
        return jax.lax.psum_scatter(gp, axis, scatter_dimension=0,
                                    tiled=True)

    # check_vma=False for symmetry with the compressed impls: the sharded
    # output spec is exactly what psum_scatter produces, but the static
    # varying-mesh-axes analysis on this jax version can't always prove it
    return jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )(x)


def reduce_scatter_flat(
    x: jnp.ndarray, mesh: Mesh, axis: Optional[str] = None
) -> jnp.ndarray:
    """Sum-reduce (N, L) into per-device owner segments: device j ends up
    holding segment j of the pod sum — a flat ``(n·ceil(L/n),)`` array
    sharded over ``axis`` whose first L elements, concatenated, are the
    sum. The first half of the hierarchical wire plan (the reference's
    intra-machine NCCL reduce-scatter before COPYD2H): each link carries
    (n−1)/n · L elements instead of allreduce's 2(n−1)/n, and each host
    only ever needs its own segments off the device.

    The tail half is :func:`all_gather_flat`; reduce-scatter + all-gather
    moves the same total bytes as one allreduce, but lets the DCN round
    trip (and per-owner compression) happen on the scattered form.
    """
    axis = axis or mesh.axis_names[0]
    _count_dispatch("reduce_scatter")
    n = mesh.shape[axis]
    raw = (n - 1) * (-(-x.shape[1] // n)) * jnp.dtype(x.dtype).itemsize
    _account_wire(raw, raw)
    return _reduce_scatter_impl(x, mesh=mesh, axis=axis)


@functools.partial(jax.jit, static_argnames=("axis", "mesh"))
def _all_gather_impl(x, *, mesh: Mesh, axis: str):
    def inner(seg):
        return jax.lax.all_gather(seg, axis, axis=0, tiled=True)

    return jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(),
        check_vma=False,
    )(x)


def all_gather_flat(
    x: jnp.ndarray,
    mesh: Mesh,
    axis: Optional[str] = None,
    length: Optional[int] = None,
) -> jnp.ndarray:
    """Replicate per-device segments back into one flat vector: the
    ``(n·seg,)`` array sharded over ``axis`` (the layout
    :func:`reduce_scatter_flat` produces, and the layout the sharded
    COPYH2D stage device_puts) becomes a replicated ``(length,)`` result —
    the hierarchical tail (the reference's BROADCAST after COPYH2D).
    Exact: gathering moves bits, never sums."""
    axis = axis or mesh.axis_names[0]
    _count_dispatch("all_gather")
    n = mesh.shape[axis]
    raw = (n - 1) * (x.shape[0] // max(1, n)) * jnp.dtype(x.dtype).itemsize
    _account_wire(raw, raw)
    out = _all_gather_impl(x, mesh=mesh, axis=axis)
    if length is not None and length != out.shape[0]:
        out = jax.lax.slice_in_dim(out, 0, length, axis=0)
    return out


@functools.partial(jax.jit, static_argnames=("axis", "root", "mesh"))
def _broadcast_impl(x, *, mesh: Mesh, axis: str, root: int):
    def inner(blk):
        mine = blk[0]
        idx = jax.lax.axis_index(axis)
        contrib = jnp.where(idx == root, mine, jnp.zeros_like(mine))
        return jax.lax.psum(contrib, axis)

    return jax.shard_map(inner, mesh=mesh, in_specs=P(axis), out_specs=P())(x)


def broadcast_flat(
    x: jnp.ndarray, mesh: Mesh, root: int = 0, axis: Optional[str] = None
) -> jnp.ndarray:
    """Row ``root`` of (N, L) → replicated (L,).

    Implemented as zero-on-non-root + psum, exactly how the reference
    implements ``broadcast_parameters`` (byteps/torch/__init__.py).
    """
    axis = axis or mesh.axis_names[0]
    _count_dispatch("broadcast")
    n = mesh.shape[axis]
    # accounted as the psum it is implemented with
    raw = 2 * (n - 1) * (-(-x.shape[1] // n)) * jnp.dtype(x.dtype).itemsize
    _account_wire(raw, raw)
    return _broadcast_impl(x, mesh=mesh, axis=axis, root=root)


# --- compressed-collective building blocks -----------------------------------
def _exchange(payload, axis: str, n: int, tier: str):
    """Deliver row j of each device's payload tree to owner j, stacked in
    WORKER order — ``all_to_all`` semantics. ``staged`` moves the whole
    tree in one collective; ``ring`` rotates one segment-payload per link
    per hop (n−1 mutually independent hops, DMA overlapping codec work).
    Both move bits only, so the stacks are bitwise identical — the
    transport is swappable under the shared aggregation arithmetic."""
    if tier == "ring":
        return jax.tree.map(lambda a: ring_collect(a, axis, n), payload)
    return jax.tree.map(
        lambda a: jax.lax.all_to_all(a, axis, 0, 0, tiled=True), payload
    )


def _gather(out_payload, axis: str, n: int, tier: str):
    """Owner-ordered stack of every owner's result payload — the "pull"
    direction (compressed when two_way/presummable). Exact either way:
    a gather moves bits, never sums."""
    if tier == "ring":
        return jax.tree.map(lambda a: ring_allgather(a, axis, n),
                            out_payload)
    return jax.tree.map(
        lambda a: jax.lax.all_gather(a, axis, axis=0, tiled=False),
        out_payload,
    )


# Beyond this many workers the unrolled fold's program size stops being
# worth it and both tiers fall back to one reduce op — at which point the
# ring-vs-staged bitwise pin is no longer structural (XLA may lower the
# two programs' reduces differently); the tests pin n = 8.
_FOLD_MAX_N = 64


def _payload_sum(recv, n: int):
    """Positional payload sum over the worker-ordered stack, as an
    UNROLLED left fold in worker order (w = 0, 1, …, n−1).

    Shared by the staged and ring paths ON PURPOSE: ``a.sum(axis=0)``
    lowers to an order XLA picks per program (measured: left fold after
    an all_to_all, a different association after the ring's assembled
    stack — a 1-ulp drift), while an explicit fold pins the association
    identically in both programs, making the deterministic-codec
    bit-exact pin structural rather than a lowering accident."""
    if n > _FOLD_MAX_N:
        return jax.tree.map(lambda a: a.sum(axis=0), recv)

    def fold(a):
        acc = a[0]
        for w in range(1, n):
            acc = acc + a[w]
        return acc

    return jax.tree.map(fold, recv)


def _compress_push(g, rng, compressor, axis, n, tier="staged"):
    """Shared COMPRESS → "PUSH" half: segment, per-segment compress,
    exchange so owner j receives every peer's segment j (stacked in
    worker order). Returns ``(payload, seg_keys, recv, seg)``.
    Per-segment rng keys must agree across devices (randomk index
    agreement, reference's synchronized-seed requirement): derive from
    the replicated base key + segment id only."""
    segs, seg = _segment(g, n)      # (n, seg): row j goes to owner j
    seg_keys = jax.vmap(lambda j: jax.random.fold_in(rng, j))(jnp.arange(n))
    payload = jax.vmap(compressor.compress)(segs, seg_keys)
    recv = _exchange(payload, axis, n, tier)
    return payload, seg_keys, recv, seg


def _ef_residual(g, payload, seg_keys, compressor, seg, L):
    """new_residual = input − D(C(input)) from the own-payload decompress
    (reference ``FastUpdateError``; no second compression)."""
    local_approx = jax.vmap(
        lambda p, k: compressor.decompress(p, seg, jnp.float32, k)
    )(payload, seg_keys)
    return g - local_approx.reshape(-1)[:L]


def compressed_allreduce_local(
    g: jnp.ndarray,
    rng: jnp.ndarray,
    compressor: Compressor,
    axis: str,
    n: int,
    average: bool = True,
    two_way: bool = True,
    ef_residual: Optional[jnp.ndarray] = None,
    return_residual: bool = False,
    tier: Optional[str] = None,
):
    """Per-device body of the compressed all-reduce.

    Call **inside** shard_map/pmap with mesh axis ``axis`` of size ``n``;
    ``g`` is this device's flat (L,) gradient chunk, ``rng`` a key
    replicated across devices. Used directly by the fused
    ``DistributedOptimizer`` path and wrapped by
    :func:`compressed_allreduce_flat` for the eager path.

    If ``ef_residual`` is given, error feedback is applied: the compressed
    input is ``g + ef_residual`` and the return value is a tuple
    ``(out, new_residual)`` with ``new_residual = input − D(C(input))``.
    ``return_residual=True`` with ``ef_residual=None`` returns the same
    tuple for a PRE-ADDED input (the fused path hoists the whole-flat
    EF add out of the per-chunk bodies so the chunk views stay pure
    reshapes): the input is taken as-is and the residual is
    ``g − D(C(g))``.

    ``tier`` selects the payload transport (``staged``/``ring``; None →
    ``BYTEPS_ICI_TIER``, resolved at trace time) — see the module
    docstring. Deterministic-codec results are bit-identical across
    tiers by construction.
    """
    tier = _resolve_tier(tier)
    L = g.shape[0]
    g = g.astype(jnp.float32)
    if n == 1 and not compressor.stochastic:
        # single-worker fast path (reference single-machine mode): no
        # exchange exists, so the whole body is one codec round trip —
        # EF add included — fusable into a single kernel pass by the
        # compressor (TopkCompressor's tiled layout does; see
        # ops/topk_kernels.py block_roundtrip). Key matches the n>1
        # path's own-segment key (fold_in(rng, 0)). DETERMINISTIC codecs
        # only: their D∘C is idempotent, so collapsing the general
        # path's two codec round trips (two_way recompression of the
        # "sum") into one changes nothing — pinned per codec in
        # tests/test_ici.py::test_n1_fast_path_*. Stochastic codecs
        # (dithering re-rounds every pass) fall through to the general
        # body, whose collectives are identities over a size-1 axis.
        dense, resid = compressor.roundtrip(
            g, jax.random.fold_in(rng, 0), e=ef_residual)
        if ef_residual is None and not return_residual:
            return dense
        return dense, resid
    if ef_residual is not None:
        g = g + ef_residual
    payload, seg_keys, recv, seg = _compress_push(
        g, rng, compressor, axis, n, tier)
    my_id = jax.lax.axis_index(axis)
    my_key = jax.random.fold_in(rng, my_id)

    if compressor.presummable:
        if tier == "ring" and compressor.stochastic:
            # genuinely fused per-hop form: accumulate the running
            # partial in payload space at every hop (payload sum == the
            # recompressed partial for presummable codecs) — chain-order
            # adds, so stochastic codecs only (statistical pin). recv is
            # unused; XLA dead-codes the collect exchange away.
            out_payload = jax.tree.map(
                lambda a: ring_presum(a, axis, n), payload)
        else:
            # positional-sum fast path: sum payloads, one decompress at
            # the end — the shared worker-order fold (see _payload_sum)
            out_payload = _payload_sum(recv, n)
    else:
        # server path: decompress each peer's segment, fp32 sum — fused
        # (Pallas on TPU) via the compressor's decompress_sum hot op
        my_keys = jnp.broadcast_to(my_key, (n,) + my_key.shape) \
            if compressor.stochastic else None
        s = compressor.decompress_sum(recv, seg, jnp.float32, my_keys)
        if two_way:
            # recompress the sum for the "PULL" direction
            out_payload = compressor.compress(s, my_key)
        else:
            out_payload = {"dense": s}

    # "PULL": broadcast owner results to everyone.
    gathered = _gather(out_payload, axis, n, tier)
    if compressor.presummable or two_way:
        all_keys = jax.vmap(lambda j: jax.random.fold_in(rng, j))(jnp.arange(n))
        out_segs = jax.vmap(
            lambda p, k: compressor.decompress(p, seg, jnp.float32, k)
        )(gathered, all_keys)
    else:
        out_segs = gathered["dense"]
    out = out_segs.reshape(-1)[:L]
    out = out / n if average else out
    if ef_residual is None and not return_residual:
        return out
    return out, _ef_residual(g, payload, seg_keys, compressor, seg, L)


def compressed_reduce_scatter_local(
    g: jnp.ndarray,
    rng: jnp.ndarray,
    compressor: Compressor,
    axis: str,
    n: int,
    average: bool = True,
    ef_residual: Optional[jnp.ndarray] = None,
    tier: Optional[str] = None,
):
    """First half of the compressed all-reduce: COMPRESS → "PUSH" → owner
    fp32 sum — WITHOUT the all_gather "PULL" back.

    Call inside shard_map. Returns this device's owned ``(ceil(L/n),)``
    fp32 segment of the aggregated gradient (the ZeRO-style sharded
    aggregation primitive: the caller applies its optimizer shard to the
    segment and all_gathers the *updates*, so the second wire direction
    carries update bytes instead of gradient bytes). With ``ef_residual``
    returns ``(segment, new_residual)`` — error feedback is identical to
    :func:`compressed_allreduce_local`'s (compress(g + residual), residual
    from the own-payload decompress). ``tier`` as in
    :func:`compressed_allreduce_local`.
    """
    tier = _resolve_tier(tier)
    L = g.shape[0]
    g = g.astype(jnp.float32)
    if n == 1 and not compressor.stochastic:
        # single-worker fast path, mirroring compressed_allreduce_local's
        # (VERDICT r8: the asymmetry): the owner "sum" over one worker is
        # D(C(g[+e])) — one fused roundtrip, EF add included. The
        # segment IS the whole vector (seg = ceil(L/1) = L, no padding)
        # and the general body's reduce-scatter never recompresses, so
        # idempotence isn't even needed — the collapse is exact for any
        # deterministic codec; pinned per codec in
        # tests/test_ring_ici.py::test_rs_n1_fast_path_*. Stochastic
        # codecs keep the general body (size-1-axis collectives are
        # identities), same gate as the allreduce fast path.
        dense, resid = compressor.roundtrip(
            g, jax.random.fold_in(rng, 0), e=ef_residual)
        if ef_residual is None:
            return dense
        return dense, resid
    if ef_residual is not None:
        g = g + ef_residual
    payload, seg_keys, recv, seg = _compress_push(
        g, rng, compressor, axis, n, tier)
    my_id = jax.lax.axis_index(axis)
    my_key = jax.random.fold_in(rng, my_id)
    if compressor.presummable:
        if tier == "ring" and compressor.stochastic:
            summed = jax.tree.map(lambda a: ring_presum(a, axis, n), payload)
        else:
            summed = _payload_sum(recv, n)
        s = compressor.decompress(summed, seg, jnp.float32, my_key)
    else:
        my_keys = jnp.broadcast_to(my_key, (n,) + my_key.shape) \
            if compressor.stochastic else None
        s = compressor.decompress_sum(recv, seg, jnp.float32, my_keys)
    s = s / n if average else s
    if ef_residual is None:
        return s
    return s, _ef_residual(g, payload, seg_keys, compressor, seg, L)


@functools.partial(
    jax.jit,
    static_argnames=("compressor", "axis", "average", "mesh", "two_way",
                     "tier"),
)
def _compressed_allreduce_impl(
    x,
    base_rng,
    *,
    compressor: Compressor,
    mesh: Mesh,
    axis: str,
    average: bool,
    two_way: bool,
    tier: str,
):
    n = mesh.shape[axis]

    def inner(blk, rng):
        return compressed_allreduce_local(
            blk[0], rng, compressor, axis, n, average=average,
            two_way=two_way, tier=tier,
        )

    # check_vma=False: the output IS replicated (it ends in an all_gather of
    # owner segments identical on every device), but the static
    # varying-mesh-axes analysis can't prove that through the tree_map'd
    # collectives.
    return jax.shard_map(
        inner, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=False,
    )(x, base_rng)


@functools.partial(
    jax.jit,
    static_argnames=("compressor", "axis", "average", "mesh", "two_way",
                     "tier"),
)
def _compressed_allreduce_ef_impl(
    x,
    ef,
    base_rng,
    *,
    compressor: Compressor,
    mesh: Mesh,
    axis: str,
    average: bool,
    two_way: bool,
    tier: str,
):
    n = mesh.shape[axis]

    def inner(blk, eblk, rng):
        out, new_e = compressed_allreduce_local(
            blk[0], rng, compressor, axis, n,
            average=average, two_way=two_way, ef_residual=eblk[0],
            tier=tier,
        )
        return out, new_e[None]

    return jax.shard_map(
        inner, mesh=mesh, in_specs=(P(axis), P(axis), P()),
        out_specs=(P(), P(axis)), check_vma=False,
    )(x, ef, base_rng)


def _require_rng(compressor: Compressor, rng):
    if rng is None:
        if compressor.stochastic:
            raise ValueError(
                f"{compressor.name} requires an rng key advancing every step"
            )
        rng = jax.random.PRNGKey(0)
    return rng


def _account_compressed(compressor: Compressor, L: int, n: int,
                        two_way: bool, pull: bool) -> None:
    """Per-device wire bytes of one compressed collective dispatch: the
    push direction always carries (n−1) compressed segment payloads; the
    pull direction (allreduce only) carries compressed owner results
    when two_way/presummable, raw fp32 segments otherwise."""
    if n <= 1:
        return
    seg = -(-L // n)
    pb = _payload_nbytes(compressor, seg)
    wire = (n - 1) * pb
    logical = (n - 1) * seg * 4
    if pull:
        wire += (n - 1) * (
            pb if (compressor.presummable or two_way) else seg * 4)
        logical *= 2
    _account_wire(wire, logical)


def compressed_allreduce_flat(
    x: jnp.ndarray,
    compressor: Compressor,
    mesh: Mesh,
    axis: Optional[str] = None,
    average: bool = True,
    rng: Optional[jnp.ndarray] = None,
    two_way: bool = True,
    ef_residual: Optional[jnp.ndarray] = None,
    tier: Optional[str] = None,
):
    """Compressed all-reduce of (N, L) → (L,).

    ``two_way=True`` compresses both directions (reference: server
    recompresses before answering pulls — lossier, max wire savings);
    ``two_way=False`` returns the exact fp32 segment sums (compress on push
    only). ``rng`` must be identical on all callers (it is, under the
    single-controller model); stochastic compressors require it.

    With ``ef_residual`` (an (N, L) per-device residual), error feedback is
    applied and ``(out, new_residual)`` is returned.

    ``tier`` picks the wire transport (None → ``BYTEPS_ICI_TIER``):
    ``staged`` all_to_all/all_gather vs the ``ring`` hop pipeline —
    bit-identical results for deterministic codecs.
    """
    axis = axis or mesh.axis_names[0]
    tier = _resolve_tier(tier)
    _count_dispatch("compressed_allreduce")
    _account_compressed(compressor, x.shape[1], mesh.shape[axis],
                        two_way, pull=True)
    rng = _require_rng(compressor, rng)
    if ef_residual is not None:
        return _compressed_allreduce_ef_impl(
            x, ef_residual, rng, compressor=compressor, mesh=mesh, axis=axis,
            average=average, two_way=two_way, tier=tier,
        )
    return _compressed_allreduce_impl(
        x, rng, compressor=compressor, mesh=mesh, axis=axis,
        average=average, two_way=two_way, tier=tier,
    )


@functools.partial(
    jax.jit,
    static_argnames=("compressor", "axis", "average", "mesh", "tier"),
)
def _compressed_reduce_scatter_impl(
    x,
    base_rng,
    *,
    compressor: Compressor,
    mesh: Mesh,
    axis: str,
    average: bool,
    tier: str,
):
    n = mesh.shape[axis]

    def inner(blk, rng):
        return compressed_reduce_scatter_local(
            blk[0], rng, compressor, axis, n, average=average, tier=tier,
        )

    return jax.shard_map(
        inner, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis),
        check_vma=False,
    )(x, base_rng)


def compressed_reduce_scatter_flat(
    x: jnp.ndarray,
    compressor: Compressor,
    mesh: Mesh,
    axis: Optional[str] = None,
    average: bool = False,
    rng: Optional[jnp.ndarray] = None,
    tier: Optional[str] = None,
):
    """Compressed reduce-scatter of (N, L): device j ends up holding the
    codec-aggregated segment j — a flat ``(n·ceil(L/n),)`` array sharded
    over ``axis``, layout-compatible with :func:`reduce_scatter_flat`
    (same padding, same trim contract downstream). The pod sum is the
    codec approximation Σ_w D(C(g_w)) in fp32 — the ``ici-compressed``
    wire: each link carries (n−1)/n · compressed bytes instead of
    (n−1)/n · L · 4. Default ``average=False`` (a REDUCE is a sum).
    ``tier`` as in :func:`compressed_allreduce_flat`."""
    axis = axis or mesh.axis_names[0]
    tier = _resolve_tier(tier)
    _count_dispatch("compressed_reduce_scatter")
    _account_compressed(compressor, x.shape[1], mesh.shape[axis],
                        two_way=False, pull=False)
    rng = _require_rng(compressor, rng)
    return _compressed_reduce_scatter_impl(
        x, rng, compressor=compressor, mesh=mesh, axis=axis,
        average=average, tier=tier,
    )
