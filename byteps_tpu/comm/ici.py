"""ICI-tier collectives: the TPU replacement for the reference's NCCL +
PS data plane on the intra-pod path (SURVEY §5.8).

Layout convention for the "eager" (per-tensor push_pull) path: a gradient
set lives as an array of shape ``(N, L)`` sharded over the mesh's dp axis on
axis 0 — row d is device d's local gradient, the analog of one reference
worker-process's GPU buffer. Collectives run inside ``shard_map`` and return
a replicated ``(L,)`` result.

The compressed all-reduce reproduces the reference's hybrid-PS dataflow
(worker compress → server decompress → fp32 sum → server recompress →
worker decompress; ``core_loops.cc`` COMPRESS/PUSH/PULL/DECOMPRESS stages +
``server.cc`` ``SumRecvBuff``) with devices as both workers and "servers":
device j owns segment j of every chunk (the analog of key→server hashing),
receives peers' compressed segments over ``all_to_all``, decompresses, sums
in fp32, recompresses, and ``all_gather``s the result. Wire bytes per
direction are (N−1)/N · compressed_size — the same ratio the reference's
colocated-server topology achieves.

Compressors whose payloads sum positionally (seed-synced randomk) skip the
decompress/recompress round trip entirely — the positional-sum fast path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.common.metrics import get_registry
from byteps_tpu.compression.base import Compressor


# handle cache keyed by registry identity (tests reset the registry):
# the dispatch path must not pay a name format + registry lookup per
# collective — the metrics design rule is handles resolved once
_dispatch_cache = {"reg": None, "counters": {}}


def _count_dispatch(kind: str) -> None:
    """Always-on ICI collective DISPATCH counter (host-side issue, not
    device completion — the quantity the ici_lock serializes and a stall
    report wants: did the host stop issuing, or did the device stop
    finishing?). One registry counter per collective family."""
    reg = get_registry()
    if _dispatch_cache["reg"] is not reg:
        _dispatch_cache["reg"] = reg
        _dispatch_cache["counters"] = {}
    c = _dispatch_cache["counters"].get(kind)
    if c is None:
        c = reg.counter(f"ici.{kind}_dispatch")
        _dispatch_cache["counters"][kind] = c
    c.inc()


def _segment(g: jnp.ndarray, n_dev: int):
    """Pad a flat (L,) vector and view as (n_dev, seg) owner-major segments."""
    L = g.shape[0]
    seg = -(-L // n_dev)
    gp = jnp.pad(g, (0, seg * n_dev - L))
    return gp.reshape(n_dev, seg), seg


@functools.partial(jax.jit, static_argnames=("axis", "average", "mesh"))
def _allreduce_impl(x, *, mesh: Mesh, axis: str, average: bool):
    n = mesh.shape[axis]

    def inner(blk):
        s = jax.lax.psum(blk[0], axis)
        return s / n if average else s

    return jax.shard_map(inner, mesh=mesh, in_specs=P(axis), out_specs=P())(x)


def allreduce_flat(
    x: jnp.ndarray, mesh: Mesh, axis: Optional[str] = None, average: bool = True
) -> jnp.ndarray:
    """Uncompressed all-reduce of (N, L) → (L,): one fused psum."""
    axis = axis or mesh.axis_names[0]
    _count_dispatch("allreduce")
    return _allreduce_impl(x, mesh=mesh, axis=axis, average=average)


@functools.partial(jax.jit, static_argnames=("axis", "mesh"))
def _reduce_scatter_impl(x, *, mesh: Mesh, axis: str):
    n = mesh.shape[axis]
    L = x.shape[1]
    seg = -(-L // n)

    def inner(blk):
        gp = jnp.pad(blk[0], (0, seg * n - L))
        return jax.lax.psum_scatter(gp, axis, scatter_dimension=0,
                                    tiled=True)

    # check_vma=False for symmetry with the compressed impls: the sharded
    # output spec is exactly what psum_scatter produces, but the static
    # varying-mesh-axes analysis on this jax version can't always prove it
    return jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )(x)


def reduce_scatter_flat(
    x: jnp.ndarray, mesh: Mesh, axis: Optional[str] = None
) -> jnp.ndarray:
    """Sum-reduce (N, L) into per-device owner segments: device j ends up
    holding segment j of the pod sum — a flat ``(n·ceil(L/n),)`` array
    sharded over ``axis`` whose first L elements, concatenated, are the
    sum. The first half of the hierarchical wire plan (the reference's
    intra-machine NCCL reduce-scatter before COPYD2H): each link carries
    (n−1)/n · L elements instead of allreduce's 2(n−1)/n, and each host
    only ever needs its own segments off the device.

    The tail half is :func:`all_gather_flat`; reduce-scatter + all-gather
    moves the same total bytes as one allreduce, but lets the DCN round
    trip (and per-owner compression) happen on the scattered form.
    """
    axis = axis or mesh.axis_names[0]
    _count_dispatch("reduce_scatter")
    return _reduce_scatter_impl(x, mesh=mesh, axis=axis)


@functools.partial(jax.jit, static_argnames=("axis", "mesh"))
def _all_gather_impl(x, *, mesh: Mesh, axis: str):
    def inner(seg):
        return jax.lax.all_gather(seg, axis, axis=0, tiled=True)

    return jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(),
        check_vma=False,
    )(x)


def all_gather_flat(
    x: jnp.ndarray,
    mesh: Mesh,
    axis: Optional[str] = None,
    length: Optional[int] = None,
) -> jnp.ndarray:
    """Replicate per-device segments back into one flat vector: the
    ``(n·seg,)`` array sharded over ``axis`` (the layout
    :func:`reduce_scatter_flat` produces, and the layout the sharded
    COPYH2D stage device_puts) becomes a replicated ``(length,)`` result —
    the hierarchical tail (the reference's BROADCAST after COPYH2D).
    Exact: gathering moves bits, never sums."""
    axis = axis or mesh.axis_names[0]
    _count_dispatch("all_gather")
    out = _all_gather_impl(x, mesh=mesh, axis=axis)
    if length is not None and length != out.shape[0]:
        out = jax.lax.slice_in_dim(out, 0, length, axis=0)
    return out


@functools.partial(jax.jit, static_argnames=("axis", "root", "mesh"))
def _broadcast_impl(x, *, mesh: Mesh, axis: str, root: int):
    def inner(blk):
        mine = blk[0]
        idx = jax.lax.axis_index(axis)
        contrib = jnp.where(idx == root, mine, jnp.zeros_like(mine))
        return jax.lax.psum(contrib, axis)

    return jax.shard_map(inner, mesh=mesh, in_specs=P(axis), out_specs=P())(x)


def broadcast_flat(
    x: jnp.ndarray, mesh: Mesh, root: int = 0, axis: Optional[str] = None
) -> jnp.ndarray:
    """Row ``root`` of (N, L) → replicated (L,).

    Implemented as zero-on-non-root + psum, exactly how the reference
    implements ``broadcast_parameters`` (byteps/torch/__init__.py).
    """
    axis = axis or mesh.axis_names[0]
    _count_dispatch("broadcast")
    return _broadcast_impl(x, mesh=mesh, axis=axis, root=root)


def _compress_push(g, rng, compressor, axis, n):
    """Shared COMPRESS → "PUSH" half: segment, per-segment compress,
    all_to_all so owner j receives every peer's segment j. Returns
    ``(payload, seg_keys, recv, seg)``. Per-segment rng keys must agree
    across devices (randomk index agreement, reference's
    synchronized-seed requirement): derive from the replicated base key +
    segment id only."""
    segs, seg = _segment(g, n)      # (n, seg): row j goes to owner j
    seg_keys = jax.vmap(lambda j: jax.random.fold_in(rng, j))(jnp.arange(n))
    payload = jax.vmap(compressor.compress)(segs, seg_keys)
    recv = jax.tree.map(
        lambda a: jax.lax.all_to_all(a, axis, 0, 0, tiled=True), payload
    )
    return payload, seg_keys, recv, seg


def _ef_residual(g, payload, seg_keys, compressor, seg, L):
    """new_residual = input − D(C(input)) from the own-payload decompress
    (reference ``FastUpdateError``; no second compression)."""
    local_approx = jax.vmap(
        lambda p, k: compressor.decompress(p, seg, jnp.float32, k)
    )(payload, seg_keys)
    return g - local_approx.reshape(-1)[:L]


def compressed_allreduce_local(
    g: jnp.ndarray,
    rng: jnp.ndarray,
    compressor: Compressor,
    axis: str,
    n: int,
    average: bool = True,
    two_way: bool = True,
    ef_residual: Optional[jnp.ndarray] = None,
    return_residual: bool = False,
):
    """Per-device body of the compressed all-reduce.

    Call **inside** shard_map/pmap with mesh axis ``axis`` of size ``n``;
    ``g`` is this device's flat (L,) gradient chunk, ``rng`` a key
    replicated across devices. Used directly by the fused
    ``DistributedOptimizer`` path and wrapped by
    :func:`compressed_allreduce_flat` for the eager path.

    If ``ef_residual`` is given, error feedback is applied: the compressed
    input is ``g + ef_residual`` and the return value is a tuple
    ``(out, new_residual)`` with ``new_residual = input − D(C(input))``.
    ``return_residual=True`` with ``ef_residual=None`` returns the same
    tuple for a PRE-ADDED input (the fused path hoists the whole-flat
    EF add out of the per-chunk bodies so the chunk views stay pure
    reshapes): the input is taken as-is and the residual is
    ``g − D(C(g))``.
    """
    L = g.shape[0]
    g = g.astype(jnp.float32)
    if n == 1 and not compressor.stochastic:
        # single-worker fast path (reference single-machine mode): no
        # exchange exists, so the whole body is one codec round trip —
        # EF add included — fusable into a single kernel pass by the
        # compressor (TopkCompressor's tiled layout does; see
        # ops/topk_kernels.py block_roundtrip). Key matches the n>1
        # path's own-segment key (fold_in(rng, 0)). DETERMINISTIC codecs
        # only: their D∘C is idempotent, so collapsing the general
        # path's two codec round trips (two_way recompression of the
        # "sum") into one changes nothing — pinned per codec in
        # tests/test_ici.py::test_n1_fast_path_*. Stochastic codecs
        # (dithering re-rounds every pass) fall through to the general
        # body, whose collectives are identities over a size-1 axis.
        dense, resid = compressor.roundtrip(
            g, jax.random.fold_in(rng, 0), e=ef_residual)
        if ef_residual is None and not return_residual:
            return dense
        return dense, resid
    if ef_residual is not None:
        g = g + ef_residual
    payload, seg_keys, recv, seg = _compress_push(g, rng, compressor, axis, n)
    my_id = jax.lax.axis_index(axis)
    my_key = jax.random.fold_in(rng, my_id)

    if compressor.presummable:
        # positional-sum fast path: sum payloads, one decompress at end
        out_payload = jax.tree.map(lambda a: a.sum(axis=0), recv)
    else:
        # server path: decompress each peer's segment, fp32 sum — fused
        # (Pallas on TPU) via the compressor's decompress_sum hot op
        my_keys = jnp.broadcast_to(my_key, (n,) + my_key.shape) \
            if compressor.stochastic else None
        s = compressor.decompress_sum(recv, seg, jnp.float32, my_keys)
        if two_way:
            # recompress the sum for the "PULL" direction
            out_payload = compressor.compress(s, my_key)
        else:
            out_payload = {"dense": s}

    # "PULL": broadcast owner results to everyone.
    gathered = jax.tree.map(
        lambda a: jax.lax.all_gather(a, axis, axis=0, tiled=False), out_payload
    )
    if compressor.presummable or two_way:
        all_keys = jax.vmap(lambda j: jax.random.fold_in(rng, j))(jnp.arange(n))
        out_segs = jax.vmap(
            lambda p, k: compressor.decompress(p, seg, jnp.float32, k)
        )(gathered, all_keys)
    else:
        out_segs = gathered["dense"]
    out = out_segs.reshape(-1)[:L]
    out = out / n if average else out
    if ef_residual is None and not return_residual:
        return out
    return out, _ef_residual(g, payload, seg_keys, compressor, seg, L)


def compressed_reduce_scatter_local(
    g: jnp.ndarray,
    rng: jnp.ndarray,
    compressor: Compressor,
    axis: str,
    n: int,
    average: bool = True,
    ef_residual: Optional[jnp.ndarray] = None,
):
    """First half of the compressed all-reduce: COMPRESS → "PUSH" → owner
    fp32 sum — WITHOUT the all_gather "PULL" back.

    Call inside shard_map. Returns this device's owned ``(ceil(L/n),)``
    fp32 segment of the aggregated gradient (the ZeRO-style sharded
    aggregation primitive: the caller applies its optimizer shard to the
    segment and all_gathers the *updates*, so the second wire direction
    carries update bytes instead of gradient bytes). With ``ef_residual``
    returns ``(segment, new_residual)`` — error feedback is identical to
    :func:`compressed_allreduce_local`'s (compress(g + residual), residual
    from the own-payload decompress).
    """
    L = g.shape[0]
    g = g.astype(jnp.float32)
    if ef_residual is not None:
        g = g + ef_residual
    payload, seg_keys, recv, seg = _compress_push(g, rng, compressor, axis, n)
    my_id = jax.lax.axis_index(axis)
    my_key = jax.random.fold_in(rng, my_id)
    if compressor.presummable:
        summed = jax.tree.map(lambda a: a.sum(axis=0), recv)
        s = compressor.decompress(summed, seg, jnp.float32, my_key)
    else:
        my_keys = jnp.broadcast_to(my_key, (n,) + my_key.shape) \
            if compressor.stochastic else None
        s = compressor.decompress_sum(recv, seg, jnp.float32, my_keys)
    s = s / n if average else s
    if ef_residual is None:
        return s
    return s, _ef_residual(g, payload, seg_keys, compressor, seg, L)


@functools.partial(
    jax.jit,
    static_argnames=("compressor", "axis", "average", "mesh", "two_way"),
)
def _compressed_allreduce_impl(
    x,
    base_rng,
    *,
    compressor: Compressor,
    mesh: Mesh,
    axis: str,
    average: bool,
    two_way: bool,
):
    n = mesh.shape[axis]

    def inner(blk, rng):
        return compressed_allreduce_local(
            blk[0], rng, compressor, axis, n, average=average, two_way=two_way
        )

    # check_vma=False: the output IS replicated (it ends in an all_gather of
    # owner segments identical on every device), but the static
    # varying-mesh-axes analysis can't prove that through the tree_map'd
    # collectives.
    return jax.shard_map(
        inner, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=False,
    )(x, base_rng)


@functools.partial(
    jax.jit,
    static_argnames=("compressor", "axis", "average", "mesh", "two_way"),
)
def _compressed_allreduce_ef_impl(
    x,
    ef,
    base_rng,
    *,
    compressor: Compressor,
    mesh: Mesh,
    axis: str,
    average: bool,
    two_way: bool,
):
    n = mesh.shape[axis]

    def inner(blk, eblk, rng):
        out, new_e = compressed_allreduce_local(
            blk[0], rng, compressor, axis, n,
            average=average, two_way=two_way, ef_residual=eblk[0],
        )
        return out, new_e[None]

    return jax.shard_map(
        inner, mesh=mesh, in_specs=(P(axis), P(axis), P()),
        out_specs=(P(), P(axis)), check_vma=False,
    )(x, ef, base_rng)


def compressed_allreduce_flat(
    x: jnp.ndarray,
    compressor: Compressor,
    mesh: Mesh,
    axis: Optional[str] = None,
    average: bool = True,
    rng: Optional[jnp.ndarray] = None,
    two_way: bool = True,
    ef_residual: Optional[jnp.ndarray] = None,
):
    """Compressed all-reduce of (N, L) → (L,).

    ``two_way=True`` compresses both directions (reference: server
    recompresses before answering pulls — lossier, max wire savings);
    ``two_way=False`` returns the exact fp32 segment sums (compress on push
    only). ``rng`` must be identical on all callers (it is, under the
    single-controller model); stochastic compressors require it.

    With ``ef_residual`` (an (N, L) per-device residual), error feedback is
    applied and ``(out, new_residual)`` is returned.
    """
    axis = axis or mesh.axis_names[0]
    _count_dispatch("compressed_allreduce")
    if rng is None:
        if compressor.stochastic:
            raise ValueError(
                f"{compressor.name} requires an rng key advancing every step"
            )
        rng = jax.random.PRNGKey(0)
    if ef_residual is not None:
        return _compressed_allreduce_ef_impl(
            x, ef_residual, rng, compressor=compressor, mesh=mesh, axis=axis,
            average=average, two_way=two_way,
        )
    return _compressed_allreduce_impl(
        x, rng, compressor=compressor, mesh=mesh, axis=axis,
        average=average, two_way=two_way,
    )
