"""Data-movement layer.

Reference analogs: ``byteps/common/nccl_manager.cc`` (intra-node NCCL) →
``comm/ici.py`` (XLA collectives over the ICI mesh inside shard_map);
``3rdparty/ps-lite`` + ``byteps/common/shared_memory.cc`` (inter-node
push/pull) → ``comm/dcn.py`` (DCN parameter-server client).
"""

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()

from byteps_tpu.comm.mesh import device_mesh, local_device_count  # noqa: F401
from byteps_tpu.comm.ici import (  # noqa: F401
    allreduce_flat,
    broadcast_flat,
    compressed_allreduce_flat,
    compressed_allreduce_local,
    compressed_reduce_scatter_flat,
    compressed_reduce_scatter_local,
)
