"""Multi-host rendezvous: ``jax.distributed`` bring-up from ``DMLC_*`` env.

Reference analog: ps-lite's scheduler node (``3rdparty/ps-lite``
postoffice rendezvous) — every worker connects to ``DMLC_PS_ROOT_URI:PORT``,
gets a rank, and joins the group before training starts (SURVEY §3.1).
Here the same env vars feed ``jax.distributed.initialize``: worker
``DMLC_WORKER_ID`` of ``DMLC_NUM_WORKER`` total joins the coordination
service hosted by worker 0 at ``DMLC_PS_ROOT_URI:BYTEPS_JAX_COORD_PORT``
(default: the DMLC root port — the exact address reference launch scripts
already point at their scheduler).

Two distributed topologies coexist (SURVEY §5.8 inter-node row):

* **hybrid PS** (default when ``DMLC_NUM_WORKER > 1``): every worker is its
  own JAX runtime over its own pod; pods aggregate through the C++
  summation servers over DCN. No ``jax.distributed``.
* **global mesh** (``BYTEPS_JAX_DISTRIBUTED=1``): the workers form ONE JAX
  process group; ``device_mesh()`` spans all hosts and XLA collectives ride
  ICI within a slice and DCN across slices (the "multislice collectives"
  alternative the survey names). The PS tier is bypassed —
  ``Config.is_distributed`` turns off so aggregation is pure collectives.
"""

from __future__ import annotations

import threading

from byteps_tpu.common.config import Config, get_config
from byteps_tpu.common.logging import get_logger

log = get_logger("comm.distributed")

_lock = threading.Lock()
_initialized = False


def maybe_init_distributed(cfg: Config | None = None) -> bool:
    """Join the global JAX process group if this job asks for one.

    Must run before the first JAX backend touch (the launcher interposes
    ``byteps_tpu._jd_boot`` so this happens before user code; calling it
    again from ``bps.init()`` is a no-op). Returns True when this process
    is part of a multi-process group.
    """
    global _initialized
    cfg = cfg or get_config()
    if not cfg.jax_distributed or cfg.num_worker <= 1:
        return False
    with _lock:
        if _initialized:
            return True
        import jax

        try:  # user (or another launcher) may have initialized it already
            if jax.distributed.is_initialized():
                _initialized = True
                return True
        except AttributeError:  # older jax without is_initialized
            pass
        coordinator = f"{cfg.jax_coord_uri}:{cfg.jax_coord_port}"
        log.info(
            "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
            coordinator, cfg.num_worker, cfg.worker_id,
        )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=cfg.num_worker,
            process_id=cfg.worker_id,
        )
        _initialized = True
        # Deliberately NO device/process queries here: they would
        # instantiate the backend NOW, locking in whatever platform the
        # interpreter started with — before user code (or a launcher-run
        # script) gets to pick one. The coordination service itself is
        # backend-free.
        log.info("joined jax.distributed group as process %d/%d",
                 cfg.worker_id, cfg.num_worker)
        return True


def is_multiprocess() -> bool:
    """True when this process runs inside a multi-process JAX group."""
    if not _initialized:
        return False
    import jax

    return jax.process_count() > 1
