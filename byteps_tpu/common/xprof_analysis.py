"""Device-trace (xprof) attribution for bench workloads.

``jax.profiler`` captures fire per-kernel events on the DEVICE timeline
with hardware timestamps (reference analog: the role BytePS' per-stage
chrome traces + server timelines play for its pipeline, SURVEY §5.1 —
here the device side, which the reference reads out of nvprof instead).
Those timestamps are the one timing source on this environment's
tunneled TPU that is *physically accountable end to end*: a chained
4096³ bf16 matmul measures 707.8 µs/matmul in the device trace = 194
TFLOP/s = 98.5% of the v5e's 197 TFLOP/s peak, agreeing with
``bench.py``'s calibration slope (BENCH_r04: 194.1) while host-side
timing fails its linearity gate in both directions
(docs/performance.md).

Primary data source: the ``*.xplane.pb`` protobuf the profiler writes
(parsed with tensorflow's bundled xplane proto), whose "XLA Ops" line
carries ``hlo_category`` per op — XLA's own MXU-vs-VPU-vs-copy verdict
("convolution fusion" = MXU work, "loop fusion" = elementwise/VPU,
"custom-call" = Pallas kernels, ...). The gzipped chrome trace next to
it has the same events but fusion names only; it remains the fallback
when no tensorflow is importable.

CLI::

    python -m byteps_tpu.common.xprof_analysis TRACE_DIR [--module NAME]

where TRACE_DIR is what ``jax.profiler.start_trace`` received (e.g.
``$BYTEPS_TRACE_DIR/xprof_rank0`` from ``BYTEPS_TRACE_XPROF=1``, or
``bench.py --mode profile``'s output dir).
"""

from __future__ import annotations

import collections
import dataclasses
import glob
import gzip
import json
import os
import re
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class KernelStat:
    name: str            # HLO instruction (result shape included)
    category: str        # hlo_category (xplane) or name-pattern bucket
    count: int
    total_us: float


@dataclasses.dataclass
class StepProfile:
    """Aggregated attribution over the captured module executions."""

    module: str                       # jit_<name>
    n_steps: int
    step_us: float                    # MEAN device span per execution —
                                      # the same denominator as every
                                      # per-step kernel/category number
                                      # (totals / n), so percentages sum
                                      # to <= 100% and gap_us is exact
    kernels: List[KernelStat]         # sorted by total_us desc
    category_us: Dict[str, float]     # per-step, summed by category
    gap_us: float                     # per-step device idle inside spans

    def table(self, top: int = 20) -> str:
        lines = [
            f"module {self.module}: {self.n_steps} executions, "
            f"{self.step_us / 1e3:.3f} ms/step on-device",
            f"{'hlo category':<26}{'ms/step':>10}{'% of step':>11}",
        ]
        for c, us in sorted(self.category_us.items(), key=lambda kv: -kv[1]):
            lines.append(
                f"{c:<26}{us/1e3:>10.3f}{100*us/self.step_us:>10.1f}%")
        lines.append(f"{'gap (in-step idle)':<26}{self.gap_us/1e3:>10.3f}"
                     f"{100*self.gap_us/self.step_us:>10.1f}%")
        lines.append("")
        lines.append(f"{'op (top by time)':<56}{'category':<22}{'count':>6}"
                     f"{'ms/step':>9}{'%':>7}")
        for k in self.kernels[:top]:
            per_step = k.total_us / self.n_steps
            lines.append(
                f"{k.name[:55]:<56}{k.category[:21]:<22}{k.count:>6}"
                f"{per_step/1e3:>9.3f}{100*per_step/self.step_us:>6.1f}%")
        return "\n".join(lines)


def _profile_run_dir(trace_dir: str) -> str:
    runs = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    if not runs:
        raise FileNotFoundError(
            f"no plugins/profile/* run under {trace_dir!r} — was the "
            "capture stopped?")
    return runs[-1]


# ---------------------------------------------------------------------------
# primary path: xplane.pb (hlo_category per op)
# ---------------------------------------------------------------------------

def _load_xplane(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: PLC0415

    files = sorted(glob.glob(
        os.path.join(_profile_run_dir(trace_dir), "*.xplane.pb")))
    if not files:
        raise FileNotFoundError("no *.xplane.pb in the profile run dir")
    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "/device:" in plane.name and any(
                l.name == "XLA Ops" for l in plane.lines):
            return plane
    raise RuntimeError(
        f"no device plane with an 'XLA Ops' line in {files[-1]!r} "
        f"(planes: {[p.name for p in xs.planes]})")


def attribute_xplane(trace_dir: str,
                     module: Optional[str] = None) -> StepProfile:
    plane = _load_xplane(trace_dir)
    smd = {k: v.name for k, v in plane.stat_metadata.items()}
    emd = plane.event_metadata

    def line(name):
        for l in plane.lines:
            if l.name == name:
                return l
        return None

    mod_line, ops_line = line("XLA Modules"), line("XLA Ops")
    if mod_line is None or ops_line is None:
        raise RuntimeError(
            "device plane lacks an 'XLA Modules'/'XLA Ops' line — "
            "falling back to the chrome trace")
    # dominant module = most total device time (the train step, not the
    # little fence/_reduce_sum programs the timing machinery also runs)
    by_mod = collections.defaultdict(list)
    for ev in mod_line.events:
        nm = emd[ev.metadata_id].name
        if module is None or module in nm:
            by_mod[nm].append(ev)
    if not by_mod:
        raise RuntimeError(f"no XLA module matching {module!r}")
    mod_name, mod_events = max(
        by_mod.items(), key=lambda kv: sum(e.duration_ps for e in kv[1]))
    spans = sorted((e.offset_ps, e.offset_ps + e.duration_ps)
                   for e in mod_events)
    n = len(mod_events)
    step_us = sum(e.duration_ps for e in mod_events) / n / 1e6

    def in_module(off):
        import bisect
        i = bisect.bisect_right(spans, (off, float("inf"))) - 1
        return i >= 0 and spans[i][0] <= off < spans[i][1]

    agg: Dict[str, KernelStat] = {}
    busy_ps = 0
    for ev in ops_line.events:
        if not in_module(ev.offset_ps):
            continue
        md = emd[ev.metadata_id]
        cat = "?"
        for st in list(ev.stats) + list(md.stats):
            if smd.get(st.metadata_id) == "hlo_category":
                cat = st.str_value or cat
                break
        st_ = agg.get(md.name)
        if st_ is None:
            agg[md.name] = KernelStat(md.name, cat, 1, ev.duration_ps / 1e6)
        else:
            st_.count += 1
            st_.total_us += ev.duration_ps / 1e6
        busy_ps += ev.duration_ps
    kernels = sorted(agg.values(), key=lambda k: -k.total_us)
    category_us: Dict[str, float] = collections.defaultdict(float)
    for k in kernels:
        category_us[k.category] += k.total_us / n
    gap = max(0.0, step_us - busy_ps / 1e6 / n)
    return StepProfile(module=mod_name, n_steps=n, step_us=step_us,
                       kernels=kernels, category_us=dict(category_us),
                       gap_us=gap)


# ---------------------------------------------------------------------------
# fallback path: chrome trace json (fusion names only)
# ---------------------------------------------------------------------------

_BUCKETS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("collective", ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")),
    ("convolution fusion", ("convolution", "dot", "gemm")),
    ("copy", ("copy", "transpose", "bitcast")),
    ("custom-call", ("custom-call", "jvp_jit", "pallas")),
    ("scatter/gather/sort", ("scatter", "gather", "sort", "top-k")),
)


def _bucket_of(name: str) -> str:
    nl = name.lower()
    for bucket, pats in _BUCKETS:
        for p in pats:
            if p in nl:
                return bucket
    return "loop fusion"


_MODULE_RE = re.compile(r"^jit_\w+\(\d+\)$|^jit_\w+$|^pjit_\w+")


def attribute_trace_json(trace_dir: str,
                         module: Optional[str] = None) -> StepProfile:
    files = sorted(glob.glob(
        os.path.join(_profile_run_dir(trace_dir), "*.trace.json.gz")))
    if not files:
        raise FileNotFoundError("no *.trace.json.gz in the profile run dir")
    with gzip.open(files[-1], "rt") as f:
        trace = json.load(f)
    evs = trace.get("traceEvents", [])
    lanes = {e["pid"]: e.get("args", {}).get("name", "")
             for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = {p for p, nm in lanes.items() if "/device:" in nm.lower()}
    dev = [e for e in evs
           if e.get("ph") == "X" and e.get("pid") in dev_pids
           and "dur" in e and "ts" in e]
    spans = [e for e in dev if _MODULE_RE.match(e["name"])
             and (module is None or module in e["name"])]
    if not spans:
        raise RuntimeError("no jit_* module spans on the device lane")
    by_mod = collections.defaultdict(list)
    for e in spans:
        by_mod[e["name"]].append(e)
    mod_name, mod_spans = max(
        by_mod.items(), key=lambda kv: sum(e["dur"] for e in kv[1]))
    mod_spans.sort(key=lambda e: e["ts"])
    n = len(mod_spans)
    step_us = sum(e["dur"] for e in mod_spans) / n
    agg: Dict[str, KernelStat] = {}
    busy = 0.0
    for s in mod_spans:
        t0, t1 = s["ts"], s["ts"] + s["dur"]
        for e in dev:
            if (e is s or _MODULE_RE.match(e["name"])
                    or not (t0 <= e["ts"] and e["ts"] + e["dur"] <= t1)):
                continue
            st = agg.get(e["name"])
            if st is None:
                agg[e["name"]] = KernelStat(
                    e["name"], _bucket_of(e["name"]), 1, e["dur"])
            else:
                st.count += 1
                st.total_us += e["dur"]
            busy += e["dur"]
    kernels = sorted(agg.values(), key=lambda k: -k.total_us)
    category_us: Dict[str, float] = collections.defaultdict(float)
    for k in kernels:
        category_us[k.category] += k.total_us / n
    return StepProfile(module=mod_name, n_steps=n, step_us=step_us,
                       kernels=kernels, category_us=dict(category_us),
                       gap_us=max(0.0, step_us - busy / n))


def attribute(trace_dir: str, module: Optional[str] = None) -> StepProfile:
    """xplane (hlo_category) when tensorflow is importable and the
    capture carries a usable device plane, else the chrome-trace
    fallback with name-pattern buckets (same run dir, fusion names
    only). Raises only when both sources fail."""
    try:
        return attribute_xplane(trace_dir, module=module)
    except (ImportError, FileNotFoundError, RuntimeError):
        return attribute_trace_json(trace_dir, module=module)


def profile_fn(fn, trace_dir: str, steps: int = 8, warmup: int = 1,
               module: Optional[str] = None) -> StepProfile:
    """Capture ``steps`` calls of ``fn`` (which must block until its
    step's work is done, e.g. via a fence) and attribute the trace.
    ``warmup`` calls run outside the window (compile + cache warm)."""
    import jax

    for _ in range(max(1, warmup)):
        fn()
    jax.profiler.start_trace(trace_dir)
    try:
        for _ in range(steps):
            fn()
    finally:
        jax.profiler.stop_trace()
    return attribute(trace_dir, module=module)


def main(argv: Optional[List[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("trace_dir")
    ap.add_argument("--module", default=None,
                    help="jit_* module name substring (default: dominant)")
    ap.add_argument("--top", type=int, default=20)
    args = ap.parse_args(argv)
    prof = attribute(args.trace_dir, module=args.module)
    print(prof.table(top=args.top))


if __name__ == "__main__":
    main()
