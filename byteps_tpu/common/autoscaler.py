"""Telemetry-driven autoscaling: ONE policy for train workers and serve
replicas.

PR 5 built scale-DOWN (leases, eviction, quorum sums, rejoin), this PR's
``kJoin`` builds scale-UP — this module closes the loop with the policy
that DECIDES. The always-on registry (PR 6) already exports everything a
Pollux-style goodput policy needs: per-worker goodput trend, the
``server.staleness`` histogram, the ``psworker.*.rounds_ahead`` straggler
gauges on the train side; queue depth and the ``serve.ttft_ms`` histogram
on the serve side. :class:`ScalingPolicy` reads a domain-agnostic
:class:`Sample` distilled from those and emits ``admit``/``evict``/
``hold`` with hysteresis, a sustain requirement, and a cooldown — the
same class drives worker admission in a training loop and replica
spawn/drain in ``serve/router.py``, so train and serve share one
elasticity story.

Every consequential decision — whether it came from this policy, the
serve router's lease sweep, or an operator-driven ``join()`` — flows
through :func:`record_decision`: the ``autoscaler.decisions`` counter,
a chrome-trace FAULT instant, and a flight-recorder event, so a
post-mortem shows WHY a worker/replica was admitted or evicted
(docs/observability.md).

Decision semantics (pinned by a deterministic trace test):

* **admit** — ``load`` held above ``scale_up_load × (1 + hysteresis)``
  for ``sustain`` consecutive samples (sustained headroom/demand, not
  one lucky step) and the unit count is below ``max_units``.
* **evict** — either a straggler was detected (``straggler`` above
  ``straggler_limit`` for ``sustain`` samples — evict it rather than let
  it set the step time) or ``load`` held below
  ``scale_down_load × (1 − hysteresis)`` (sustained idleness), and the
  unit count is above ``min_units``.
* **hold** — inside the hysteresis band, during the post-decision
  cooldown, or pinned at a min/max bound.

``load`` is the domain's demand/efficiency signal, HIGH = the pool is
earning its keep: per-worker goodput as a fraction of the clean
per-worker baseline (train, :func:`train_sample`), or per-replica queue
depth plus TTFT-SLO pressure (serve, :func:`serve_sample`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from byteps_tpu.common.config import get_config
from byteps_tpu.common.flight_recorder import get_flight_recorder
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.common.tracing import get_tracer

log = get_logger("autoscaler")

__all__ = [
    "Sample", "Decision", "ScalingPolicy", "record_decision",
    "train_sample", "serve_sample",
]


def record_decision(domain: str, action: str, reason: str,
                    target: Optional[int] = None,
                    live: Optional[int] = None,
                    predicted: Optional[Dict[str, Any]] = None) -> None:
    """The ONE event path for every scale decision: counters
    (``autoscaler.decisions`` + ``autoscaler.<domain>.<action>``), a
    chrome-trace FAULT instant, and a flight-recorder event. The serve
    router's lease sweep and the policy loop both land here, so a
    post-mortem's event ring answers "why was this worker/replica
    admitted/evicted" uniformly. ``predicted`` carries the what-if
    simulator's payoff estimate when an ``estimator`` was consulted —
    the post-mortem then also answers "what did the decision EXPECT"."""
    reg = get_registry()
    reg.counter("autoscaler.decisions").inc()
    reg.counter(f"autoscaler.{domain}.{action}").inc()
    args = {"domain": domain, "action": action, "reason": reason,
            "target": target, "live": live}
    if predicted is not None:
        args["predicted"] = predicted
    get_tracer().instant(f"autoscaler_{action}", "FAULT", args)
    get_flight_recorder().record_event("autoscaler.decision", args)
    log.info("autoscaler[%s]: %s (%s)%s", domain, action, reason,
             f" target={target}" if target is not None else "")


@dataclasses.dataclass(frozen=True)
class Sample:
    """One domain-agnostic policy observation (see module docstring)."""

    live: int               # current live unit count (workers/replicas)
    load: float             # demand/efficiency signal, HIGH = earning keep
    straggler: float = 0.0  # straggler severity (rounds_ahead spread /
    #                         staleness p99 / replica load imbalance)


@dataclasses.dataclass(frozen=True)
class Decision:
    action: str   # 'admit' | 'evict' | 'hold'
    reason: str
    step: int     # the policy step this decision was made at
    live: int     # unit count observed when deciding
    # the estimator's payoff prediction, when one was consulted:
    # {"goodput_live", "goodput_target", "target"} — recorded on the
    # decision event for post-mortems (ROADMAP item 4's remainder)
    predicted: Optional[Dict[str, float]] = None


class ScalingPolicy:
    """Hysteresis/sustain/cooldown admit-evict-hold policy — one class
    for both elasticity domains (constructor thresholds carry the
    domain's units; the dynamics come from the shared
    ``BYTEPS_AUTOSCALE_*`` defaults)."""

    def __init__(self, scale_up_load: float, scale_down_load: float,
                 straggler_limit: Optional[float] = None,
                 hysteresis: Optional[float] = None,
                 cooldown: Optional[int] = None,
                 sustain: Optional[int] = None,
                 min_units: Optional[int] = None,
                 max_units: Optional[int] = None,
                 domain: str = "train",
                 estimator: Optional[Callable[[int], float]] = None):
        """``estimator(n_units) -> predicted aggregate goodput`` (the
        what-if simulator's ``sim.search.goodput_estimator``, or any
        model): when set, an ADMIT must predict its own payoff before
        spending capacity — the marginal unit must add at least
        ``hysteresis`` of an average live unit's current contribution
        (a per-unit margin: perfect linear scaling always passes), else
        the decision degrades to a hold that says so and arms the
        cooldown like the admit it replaced. Every estimator
        consultation is recorded on the decision
        (``Decision.predicted``) and rides the shared event path, so
        post-mortems show expectation beside outcome."""
        cfg = get_config()
        if scale_down_load >= scale_up_load:
            raise ValueError(
                f"scale_down_load ({scale_down_load}) must sit below "
                f"scale_up_load ({scale_up_load}) — an inverted band "
                "admits and evicts at once")
        self.scale_up_load = float(scale_up_load)
        self.scale_down_load = float(scale_down_load)
        self.straggler_limit = straggler_limit
        self.hysteresis = (hysteresis if hysteresis is not None
                           else cfg.autoscale_hysteresis)
        self.cooldown = (cooldown if cooldown is not None
                         else cfg.autoscale_cooldown)
        self.sustain = max(1, sustain if sustain is not None
                           else cfg.autoscale_sustain)
        self.min_units = (min_units if min_units is not None
                          else cfg.autoscale_min)
        self.max_units = (max_units if max_units is not None
                          else cfg.autoscale_max)
        self.domain = domain
        self.estimator = estimator
        self._step = 0
        self._last_change = -(10 ** 9)
        self._up_streak = 0
        self._down_streak = 0
        self._straggler_streak = 0
        # full decision history — what the deterministic-trace pin and
        # the churn bench artifact read back
        self.trace: List[Decision] = []
        self._m_hold = get_registry().counter(
            f"autoscaler.{domain}.hold")

    # -- core ---------------------------------------------------------------
    def observe(self, sample: Sample) -> Decision:
        """Feed one sample; returns (and records) the decision. Non-hold
        decisions reset the streaks and arm the cooldown; the CALLER
        executes them (join a worker / spawn a replica / drain one) —
        the policy only decides."""
        self._step += 1
        d = self._decide(sample)
        self.trace.append(d)
        if d.action == "hold" and not (d.predicted is not None
                                       and "veto" in d.reason):
            # holds are counted but not traced/ring-recorded: one event
            # per policy tick would drown the post-mortem ring
            self._m_hold.inc()
        elif d.action == "hold":
            # an estimator VETO is a consequential decision (capacity
            # was declined on a predicted non-payoff) and must be
            # explicable post-mortem like the admit it replaced — and it
            # arms the cooldown + resets the streaks exactly like one,
            # so a sustained veto state records once per cooldown window
            # instead of once per tick (which would drown the ring).
            # record_decision counts autoscaler.<domain>.hold itself.
            record_decision(self.domain, "hold", d.reason,
                            live=sample.live, predicted=d.predicted)
            self._last_change = self._step
            self._up_streak = self._down_streak = 0
            self._straggler_streak = 0
        else:
            record_decision(self.domain, d.action, d.reason,
                            live=sample.live, predicted=d.predicted)
            self._last_change = self._step
            self._up_streak = self._down_streak = 0
            self._straggler_streak = 0
        return d

    def _decide(self, s: Sample) -> Decision:
        up_at = self.scale_up_load * (1.0 + self.hysteresis)
        down_at = self.scale_down_load * (1.0 - self.hysteresis)
        # streaks advance even during the cooldown so a persistent
        # condition acts the moment the cooldown opens
        if (self.straggler_limit is not None
                and s.straggler > self.straggler_limit):
            self._straggler_streak += 1
        else:
            self._straggler_streak = 0
        self._up_streak = self._up_streak + 1 if s.load >= up_at else 0
        self._down_streak = (self._down_streak + 1 if s.load <= down_at
                             else 0)
        if self._step - self._last_change <= self.cooldown:
            return Decision("hold", "cooldown", self._step, s.live)
        if self._straggler_streak >= self.sustain:
            if s.live > self.min_units:
                return Decision(
                    "evict",
                    f"straggler detected ({s.straggler:.3g} > "
                    f"{self.straggler_limit:.3g} for "
                    f"{self._straggler_streak} samples)",
                    self._step, s.live)
            return Decision("hold", "straggler but at min_units",
                            self._step, s.live)
        if self._up_streak >= self.sustain:
            if s.live < self.max_units:
                reason = (f"sustained load headroom ({s.load:.3g} >= "
                          f"{up_at:.3g} for {self._up_streak} samples)")
                pred = self._predict(s.live, s.live + 1)
                if pred is not None and not pred["pays_off"]:
                    # ROADMAP item 4's remainder: the admit predicts its
                    # own payoff (simulated goodput at live+1) BEFORE
                    # spending capacity — a sublinear step (round-close
                    # barriers, server contention) turns into a hold
                    return Decision(
                        "hold",
                        f"estimator veto: goodput({s.live + 1}) "
                        f"{pred['goodput_target']:.3g} adds under "
                        f"{self.hysteresis:.3g}x of an average "
                        f"worker's share at live {s.live} "
                        f"({pred['goodput_live']:.3g})",
                        self._step, s.live, predicted=pred)
                return Decision("admit", reason, self._step, s.live,
                                predicted=pred)
            return Decision("hold", "demand but at max_units",
                            self._step, s.live)
        if self._down_streak >= self.sustain:
            if s.live > self.min_units:
                return Decision(
                    "evict",
                    f"sustained idle ({s.load:.3g} <= {down_at:.3g} "
                    f"for {self._down_streak} samples)",
                    self._step, s.live,
                    # recorded, never vetoing: an idle evict SAVES
                    # capacity — the prediction is for the post-mortem
                    predicted=self._predict(s.live, s.live - 1))
            return Decision("hold", "idle but at min_units",
                            self._step, s.live)
        return Decision("hold", "in-band", self._step, s.live)

    def _predict(self, live: int, target: int,
                 ) -> Optional[Dict[str, float]]:
        """Consult the estimator (None when none attached; a failing
        estimator is treated as absent — the policy must keep deciding
        without its advisor). ``pays_off`` applies the policy's
        hysteresis as the margin an extra unit must clear."""
        if self.estimator is None:
            return None
        try:
            cur = float(self.estimator(live))
            tgt = float(self.estimator(target))
        except Exception as e:  # noqa: BLE001 — advisory, never fatal
            log.warning("autoscaler estimator failed (%s); deciding "
                        "without prediction", e)
            return None
        # an admit pays off when the MARGINAL unit delivers at least
        # `hysteresis` of an average live unit's current contribution —
        # relative to the per-unit gain, NOT the aggregate (a flat
        # aggregate margin would veto perfect linear scaling the moment
        # live exceeds 1/hysteresis)
        per_unit = cur / max(1, live)
        return {
            "goodput_live": cur,
            "goodput_target": tgt,
            "target": target,
            "pays_off": ((tgt - cur) > self.hysteresis * per_unit
                         if target > live else tgt >= 0.0),
        }


# -- domain samplers ----------------------------------------------------------
def train_sample(snapshot: Dict[str, Any], live: int,
                 goodput_per_worker: float,
                 baseline_per_worker: float) -> Sample:
    """Distill the TRAIN-domain :class:`Sample` from a
    ``byteps_tpu.metrics_snapshot()`` dict plus the caller's goodput
    trend: ``load`` = per-worker goodput as a fraction of the clean
    per-worker baseline (≈1.0 means adding capacity still pays
    linearly); ``straggler`` = the spread of the per-NIC
    ``rounds_ahead`` gauges (how far the fastest pipeline runs ahead of
    the round it consumes vs the slowest) with the ``server.staleness``
    p99 folded in — both are zero on a healthy strict-sync tier."""
    m = snapshot.get("metrics", snapshot)
    gauges = m.get("gauges", {})
    ahead = [
        float(v["value"] if isinstance(v, dict) else v)
        for k, v in gauges.items()
        if k.startswith("psworker.") and k.endswith(".rounds_ahead")
    ]
    spread = (max(ahead) - min(ahead)) if len(ahead) > 1 else 0.0
    hist = m.get("histograms", {}).get("server.staleness", {})
    stale_p99 = float(hist.get("p99", 0.0) or 0.0)
    load = (goodput_per_worker / baseline_per_worker
            if baseline_per_worker > 0 else 0.0)
    return Sample(live=int(live), load=load,
                  straggler=max(spread, stale_p99))


def serve_sample(live: int, queue_depth: float,
                 ttft_p99_ms: float = 0.0,
                 ttft_slo_ms: Optional[float] = None) -> Sample:
    """Distill the SERVE-domain :class:`Sample`: ``load`` = per-replica
    queue depth, plus SLO pressure (how far the recent TTFT overshoots
    the SLO) when an SLO is configured — a saturated-but-short queue
    with blown latency must still scale up. The TTFT figure should be a
    WINDOWED reading (the router passes the per-tick delta mean of the
    ``serve.ttft_ms`` histogram — a process-lifetime percentile would
    carry a cold-start spike forever). ``straggler`` stays 0:
    replica-level stragglers are the router's LEASE sweep's job
    (silence, not slowness)."""
    load = float(queue_depth) / max(1, int(live))
    if ttft_slo_ms and ttft_p99_ms:
        load += max(0.0, float(ttft_p99_ms) / float(ttft_slo_ms) - 1.0)
    return Sample(live=int(live), load=load, straggler=0.0)
