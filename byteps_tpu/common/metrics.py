"""Always-on telemetry plane: the unified metrics registry.

The joapolarbear fork of BytePS exists largely to feed per-stage traces
to dPRO-style attribution (PAPER.md), yet until this module the repo's
observability was opt-in and post-mortem only: chrome traces gated on
``BYTEPS_TRACE_ON``, robustness counters exported once at shutdown, and
stall diagnostics assembled ad hoc. This registry is the cheap
ALWAYS-ON layer underneath all of that: every subsystem that used to
keep a private tally (scheduler stage times, per-NIC wire bytes, pacer
token debt, ICI dispatch counts, fault injections, train-step walltime)
also lands it here, so one ``snapshot()`` — or one flight-recorder ring
entry (``common/flight_recorder.py``) — sees the whole data plane.

Design constraints, in order:

* **near-zero hot-path overhead** — a counter inc is one lock + one int
  add (sub-microsecond in CPython); a histogram observe is one bisect
  into FIXED buckets + four adds. No label dicts, no string formatting,
  no allocation on the hot path: series identity is the dotted name,
  resolved once and cached by the call site. The overhead budget is
  PINNED by a tier-1 test (tests/test_metrics.py) so it can't silently
  grow.
* **thread-safe** — every producer (scheduler pools, health monitors,
  pacer callers, retry loops) mutates concurrently; each metric carries
  its own small lock, so there is no global serialization point.
* **process-wide and failure-proof** — metrics outlive their producers:
  a retired NIC's counts stay in the registry totals (the per-PSWorker
  ``get_counters()`` view dies with the NIC; the registry's does not),
  which is what makes per-run totals complete across owner failover.

``BYTEPS_METRICS_ON=0`` swaps every handle for a shared no-op so the
hot path degenerates to one dynamic call (the escape hatch; on by
default — "always-on" is the point).
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "reset_registry", "json_safe",
    "DEFAULT_BUCKETS",
]


# Fixed 1-2-5 geometric ladder spanning 1 .. 1e8 (+inf overflow bucket):
# wide enough for µs latencies (1 µs .. 100 s) AND byte sizes (1 B ..
# 100 MB) without per-series tuning — fixed buckets are what keep
# ``observe`` allocation-free and snapshots mergeable across runs.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    m * (10 ** e) for e in range(0, 8) for m in (1, 2, 5)
)


class Counter:
    """Monotonic counter. ``inc(n)`` under a per-metric lock — the GIL
    alone does not make ``+=`` atomic across the read-modify-write."""

    __slots__ = ("_v", "_lock")

    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def value(self) -> int:
        with self._lock:
            return self._v


class Gauge:
    """Last-write-wins value that also tracks its high-water mark (the
    occupancy question a stall report asks is "how full did the credit
    pool GET", not just "what is it now")."""

    __slots__ = ("_v", "_max", "_lock")

    def __init__(self) -> None:
        self._v = 0.0
        self._max = -math.inf
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v
            if v > self._max:
                self._max = v

    def value(self) -> float:
        with self._lock:
            return self._v

    def max(self) -> float:
        with self._lock:
            return self._max if self._max != -math.inf else 0.0


class Histogram:
    """Fixed-bucket histogram with p50/p99 snapshots.

    ``observe(v)`` is one ``bisect`` into the immutable bucket edges
    plus count/sum/min/max updates — no allocation, no resizing, so the
    hot path cost is flat regardless of how much has been recorded.
    Percentiles are interpolated within the owning bucket at snapshot
    time (coarse by design: a 1-2-5 ladder bounds the error at ~2.5×
    worst-case, plenty for "did PUSH p99 move by an order of magnitude",
    which is the question a trend/stall report asks).
    """

    __slots__ = ("_edges", "_counts", "_count", "_sum", "_min", "_max",
                 "_lock")

    def __init__(self, buckets: Optional[Sequence[float]] = None) -> None:
        self._edges: Tuple[float, ...] = tuple(buckets or DEFAULT_BUCKETS)
        self._counts = [0] * (len(self._edges) + 1)  # +overflow
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect_left(self._edges, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    def _quantile_locked(self, q: float) -> float:
        if self._count == 0:
            return 0.0
        target = q * self._count
        seen = 0
        for i, c in enumerate(self._counts):
            if c == 0:
                continue
            if seen + c >= target:
                lo = self._edges[i - 1] if i > 0 else 0.0
                hi = (self._edges[i] if i < len(self._edges)
                      else max(self._max, lo))
                lo = max(lo, self._min if self._min != math.inf else lo)
                hi = min(hi, self._max if self._max != -math.inf else hi)
                if hi <= lo:
                    return lo
                frac = (target - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self._max if self._max != -math.inf else 0.0

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            return {
                "count": self._count,
                "sum": self._sum,
                "mean": self._sum / self._count,
                "min": self._min,
                "max": self._max,
                "p50": self._quantile_locked(0.50),
                "p99": self._quantile_locked(0.99),
            }

    def count(self) -> int:
        with self._lock:
            return self._count


class _Null:
    """Shared no-op standing in for every metric when the registry is
    disabled (BYTEPS_METRICS_ON=0): the hot path pays one method call."""

    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def value(self) -> int:
        return 0

    def max(self) -> float:
        return 0.0

    def snapshot(self) -> Dict[str, float]:
        return {"count": 0}

    def count(self) -> int:
        return 0


_NULL = _Null()

# Runaway-series backstop: a bug minting a fresh name per op must fill
# the registry, not the process heap. Far above any legitimate series
# count (a 4-NIC pod with every subsystem instrumented sits under ~100).
_MAX_SERIES = 4096


class MetricsRegistry:
    """Name → metric map. Creation takes the registry lock; the returned
    handle is lock-free to HOLD (call sites cache it), so steady-state
    traffic never touches the registry lock."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self.dropped_series = 0

    def _get(self, table: Dict[str, Any], name: str, factory):
        if not self.enabled:
            return _NULL
        m = table.get(name)
        if m is not None:
            return m
        with self._lock:
            m = table.get(name)
            if m is None:
                if (len(self._counters) + len(self._gauges)
                        + len(self._hists)) >= _MAX_SERIES:
                    self.dropped_series += 1
                    return _NULL
                m = factory()
                table[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(self._hists, name,
                         lambda: Histogram(buckets))

    def snapshot(self, prefix: str = "") -> Dict[str, Any]:
        """One JSON-safe view of everything: counters/gauges as scalars,
        histograms as their stat dicts. ``prefix`` filters by dotted-name
        prefix (e.g. ``"scheduler.stage."`` for the flight recorder's
        per-step stage block)."""
        with self._lock:
            counters = {k: v for k, v in self._counters.items()
                        if k.startswith(prefix)}
            gauges = {k: v for k, v in self._gauges.items()
                      if k.startswith(prefix)}
            hists = {k: v for k, v in self._hists.items()
                     if k.startswith(prefix)}
        out: Dict[str, Any] = {
            "counters": {k: c.value() for k, c in sorted(counters.items())},
            "gauges": {k: {"value": g.value(), "max": g.max()}
                       for k, g in sorted(gauges.items())},
            "histograms": {k: h.snapshot() for k, h in sorted(hists.items())},
        }
        if self.dropped_series:
            # series-cap truncation must be VISIBLE: a per-NIC counter
            # that silently became a no-op would read as zero traffic
            out["dropped_series"] = self.dropped_series
        return out

    def snapshot_scalars(self, prefix: str = "") -> Dict[str, Any]:
        """Counters + gauges only — the flight recorder's per-step view
        (histogram percentile scans are saved for the post-mortem and
        the per-step stage prefix; per-step cost must not grow with the
        process's total histogram count)."""
        with self._lock:
            counters = {k: v for k, v in self._counters.items()
                        if k.startswith(prefix)}
            gauges = {k: v for k, v in self._gauges.items()
                      if k.startswith(prefix)}
        return {
            "counters": {k: c.value() for k, c in sorted(counters.items())},
            "gauges": {k: {"value": g.value(), "max": g.max()}
                       for k, g in sorted(gauges.items())},
        }


_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry (enabled per BYTEPS_METRICS_ON at first
    use; ``reset_registry()`` re-reads — tests monkeypatch env)."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                from byteps_tpu.common.config import get_config

                _registry = MetricsRegistry(
                    enabled=get_config().metrics_on)
    return _registry


def reset_registry() -> None:
    """Drop the cached registry (tests mutate env / need isolation).
    Handles cached by live objects keep working — they just stop being
    visible in the NEW registry's snapshots."""
    global _registry
    with _registry_lock:
        _registry = None


# --- chrome-trace / telemetry arg sanitizer ---------------------------------
def json_safe(obj: Any, _depth: int = 0) -> Any:
    """Scrub a telemetry/trace ``args`` value down to plain JSON types.

    ONE definition for every producer boundary (chrome-trace events and
    metadata, flight-recorder events, post-mortem dumps): np.bool_ broke
    the trace dump once (PR 5 fixed that single call site); this makes
    ANY event arg safe — numpy scalars unwrap to their Python
    equivalents, 0-d/small arrays become lists, big arrays a shape
    descriptor, bytes decode, and anything else falls back to ``str``.
    Property-tested over the numpy scalar types in tests/test_tracing.py.
    """
    import numpy as np

    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        # np.float64 subclasses float and serializes fine; non-finite
        # values have no JSON literal, so stringify them
        return obj if math.isfinite(obj) else str(obj)
    if _depth > 8:
        return str(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        v = float(obj)
        # JSON has no inf/nan literals; json.dump would emit
        # non-standard tokens some consumers reject
        return v if math.isfinite(v) else str(v)
    if isinstance(obj, np.complexfloating):
        return str(complex(obj))
    if isinstance(obj, np.ndarray):
        if obj.ndim == 0:
            return json_safe(obj.item(), _depth + 1)
        if obj.size <= 16:
            return [json_safe(x, _depth + 1) for x in obj.tolist()]
        return f"ndarray(shape={obj.shape}, dtype={obj.dtype})"
    if isinstance(obj, (bytes, bytearray, np.bytes_)):
        return bytes(obj).decode("utf-8", errors="replace")
    if isinstance(obj, dict):
        return {str(k): json_safe(v, _depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [json_safe(v, _depth + 1) for v in obj]
    return str(obj)
