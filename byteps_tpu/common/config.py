"""Typed runtime configuration fed by ``DMLC_*`` / ``BYTEPS_*`` env vars.

The reference configures everything through environment variables (SURVEY
§5.6; reference ``docs/env.md``, parsed in ``byteps/common/global.cc`` and
``ps-lite include/ps/internal/env.h``). We keep the same names so reference
user scripts and launch wrappers work unchanged, but back them with a typed
``Config`` object used everywhere internally.

Two namespaces:

* ``DMLC_*`` — cluster topology (role, counts, rendezvous address). Consumed
  by the launcher, the DCN parameter-server tier, and ``jax.distributed``
  initialization.
* ``BYTEPS_*`` — runtime tuning (partition bytes, scheduling credit, async
  mode, tracing, log level).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return int(v)


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v.strip().lower() in ("1", "true", "on", "yes", "y")


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return float(v)


# Partition size default mirrors the reference's BYTEPS_PARTITION_BYTES
# default of 4096000 bytes (byteps/common/global.cc).
DEFAULT_PARTITION_BYTES = 4096000
# Reference BYTEPS_SCHEDULING_CREDIT default (byteps/common/scheduled_queue.cc).
DEFAULT_SCHEDULING_CREDIT = 4
# (The reference's BYTEPS_NCCL_GROUP_SIZE has no TPU analog: XLA's async
# dispatch overlaps chunk collectives on the device stream and the credit
# scheduler bounds in-flight partitions, which together subsume NCCL group
# batching — the knob is intentionally not exposed.)
DEFAULT_SERVER_ENGINE_THREADS = 4


@dataclasses.dataclass
class Config:
    """Process-wide runtime configuration (reference: ``BytePSGlobal``)."""

    # --- DMLC_* cluster topology -------------------------------------------
    role: str = "worker"  # scheduler | server | worker | joint
    num_worker: int = 1
    num_server: int = 0
    ps_root_uri: str = "127.0.0.1"
    ps_root_port: int = 9000
    worker_id: int = 0
    interface: str = ""
    # Global-mesh mode (BYTEPS_JAX_DISTRIBUTED=1): the DMLC_NUM_WORKER
    # worker processes join one jax.distributed group and device_mesh()
    # spans all hosts; aggregation is pure XLA collectives (ICI + DCN) and
    # the PS tier is bypassed. Default off = hybrid PS topology.
    jax_distributed: bool = False
    # Coordination-service address for global-mesh rendezvous, hosted by
    # WORKER 0 (reference analog: the ps-lite scheduler's address). The
    # defaults reuse DMLC_PS_ROOT_URI/PORT — correct when worker 0 lives at
    # that address (the common colocated layout; PS servers bind
    # port+1+i so there is no clash). Deployments whose DMLC_PS_ROOT_URI
    # points at a dedicated scheduler machine must set
    # BYTEPS_JAX_COORD_URI to worker 0's host instead — our scheduler role
    # is a no-op that binds nothing.
    jax_coord_uri: str = "127.0.0.1"
    jax_coord_port: int = 9000

    # --- BYTEPS_* runtime tuning -------------------------------------------
    local_rank: int = 0
    local_size: int = 1
    partition_bytes: int = DEFAULT_PARTITION_BYTES
    scheduling_credit: int = DEFAULT_SCHEDULING_CREDIT
    force_distributed: bool = False
    enable_async: bool = False
    enable_ipc: bool = False
    server_engine_threads: int = DEFAULT_SERVER_ENGINE_THREADS
    # Priority-ordered server engine (reference BYTEPS_SERVER_ENABLE_SCHEDULE
    # [C-LOW]): a contended engine sums/answers lower keys (earlier-declared,
    # higher-priority tensors) first, matching the worker scheduler's order.
    server_enable_schedule: bool = False
    # Server expires pulls waiting longer than this with an error so a dead
    # worker fails the job fast instead of hanging its peers (reference
    # analog: ps-lite heartbeat/resender timeouts). 0 disables.
    pull_timeout_ms: int = 60000
    log_level: str = "INFO"
    # compression: compress only partitions >= this many bytes (reference
    # BYTEPS_MIN_COMPRESS_BYTES semantics: tiny tensors aren't worth it).
    min_compress_bytes: int = 65536
    # Application-level DCN bandwidth emulation (no reference analog):
    # > 0 paces every PSWorker's wire payload bytes through per-direction
    # token buckets at this many megabits/s, so loopback behaves like a
    # slow cross-pod link (the regime gradient compression exists for).
    # 0 disables. See server/pacer.py and bench.py --mode throttled.
    dcn_throttle_mbps: float = 0.0
    # Sharded-wire hierarchical DCN tier (BytePS "use every link", OSDI'20
    # §hierarchical): the hybrid pipeline reduce-SCATTERs the pod instead
    # of allreducing, assigns each partition an owner controller
    # (rendezvous hash over the pod's controllers), and each owner
    # pushes/pulls only its ~1/controllers slice through its own NIC; an
    # all-gather tail reassembles before H2D. Results are bit-exact vs
    # the unsharded path (raw) / at wire-codec roundoff (compressed) —
    # pinned in tests/test_sharded_hybrid.py. Default on.
    hybrid_sharded: bool = True
    # Controller NICs the pod is modeled with (each its own PSWorker:
    # connections, pacer, fault plan). 1 = the classic single-pusher
    # wire. > 1 divides per-NIC DCN bytes by the count — the sharded
    # race bench.py --mode hybrid measures. Deliberately its own knob
    # (NOT BYTEPS_LOCAL_SIZE, which counts launcher-spawned processes).
    pod_controllers: int = 1
    # Salt of the partition→owner rendezvous hash (reshuffles placement
    # without renaming tensors; must agree across a pod's controllers).
    owner_salt: int = 0
    # Multi-slice mesh: > 1 adds a leading slice_ axis of this size to
    # make_mesh/factor_devices (real TPU pods via
    # create_hybrid_device_mesh, anywhere else emulated slice
    # boundaries). The Partitioner routes "batch" over (slice_, dp) and
    # the gradient path becomes hierarchical: per-slice ICI
    # reduce-scatter, (optionally compressed) DCN exchange over slice_,
    # ICI all-gather. See docs/architecture.md §partitioner.
    num_slices: int = 1
    # ZeRO-3 FSDP (parallel/zero3.py): params + optimizer moments live
    # as flat segments sharded over slice_ (or dp), all-gathered
    # just-in-time per layer. Launchers translate this into
    # make_gpt_train_step(zero_3=True).
    zero3: bool = False

    # --- robustness / chaos (docs/robustness.md) ---------------------------
    # Deterministic fault injection at the PSWorker wire boundary
    # (common/faults.py grammar); empty = off. Arming it also turns on
    # wire CRC so injected corruption is detected, not summed.
    fault_spec: str = ""
    fault_seed: int = 0
    # Worker-side retry engine: retryable wire errors (recv timeout, dead
    # socket, desync, CRC mismatch) are retried up to this many times per
    # op with exponential backoff (base below, x2 per attempt, capped at
    # 2 s) + seeded jitter. Replay-safe: a re-sent push carries the same
    # (worker, key, version) and the server dedupes it.
    retry_limit: int = 8
    retry_backoff_ms: int = 50
    # CRC32 on wire payloads (frame header crc field): pushes are verified
    # server-side before summing, pull responses worker-side. Off by
    # default (a software CRC pass per 4 MB partition is measurable);
    # forced on while fault injection is armed.
    wire_crc: bool = False
    # Health monitor: > 0 pings every server each interval from a
    # background thread; after `health_miss_limit` consecutive misses the
    # server is marked dead and its keys fail over to the survivors
    # (rendezvous hash over the live set). 0 disables.
    health_interval_ms: int = 0
    health_miss_limit: int = 3
    # With no live server left: True degrades push_pull to the pod-local
    # (pure-ICI) sum with a loud log + counters; False fails the handle.
    degraded_ok: bool = True
    # Elastic worker membership (docs/robustness.md): > 0 arms worker
    # LEASES on the summation servers — a worker silent past this many ms
    # (no push/pull/heartbeat) is EVICTED: the membership epoch bumps,
    # open rounds re-target the live worker set (partial sums scaled to
    # the survivors so the global average stays unbiased), stuck barriers
    # release, and the server can exit without the dead worker's goodbye.
    # Workers heartbeat through the health monitor's kPing (enable
    # BYTEPS_HEALTH_INTERVAL_MS well below the lease). 0 = fixed
    # membership (legacy: one dead worker stalls every peer).
    worker_lease_ms: int = 0
    # > 0 caps EVERY Handle.wait() at this many ms: a would-be infinite
    # wait (peer death with no lease, total stall) raises a diagnosable
    # StallError carrying per-stage/per-server counters instead of
    # blocking forever. 0 = only the caller's own timeout applies.
    handle_deadline_ms: int = 0
    # Bounded-staleness PS rounds (BYTEPS_STALENESS=K, docs/robustness.md
    # §bounded staleness): K > 0 lets the summation servers answer a pull
    # for round v from the newest CLOSED round >= v-K — and force-close a
    # straggler-held round over its contributors (quorum-scaled, exactly
    # like an eviction-shrunk round) — so one slow worker no longer sets
    # the global step time; the worker pipeline keeps K rounds of pushes
    # in flight (per-key scheduler window) while PULL consumes whatever
    # round the server serves, and responses stamp the SERVED round.
    # K=0 = today's synchronous tier, bit-identical; BYTEPS_ENABLE_ASYNC
    # is the K=inf limit and wins when both are set.
    staleness: int = 0
    # --- autoscaler policy defaults (common/autoscaler.py) -----------------
    # One ScalingPolicy class drives BOTH elasticity domains: train
    # worker admit/evict off the telemetry registry (goodput/worker
    # trend, server.staleness p99, rounds_ahead straggler spread) and
    # serve replica spawn/drain off queue depth + TTFT. These knobs are
    # the shared decision dynamics; the load thresholds themselves are
    # per-policy constructor arguments (their units differ per domain).
    # Relative dead band around each threshold — decisions fire only
    # OUTSIDE load*(1±hysteresis), so a load oscillating on a threshold
    # cannot flap the membership.
    autoscale_hysteresis: float = 0.1
    # Policy steps to HOLD after any admit/evict (lets the epoch bump,
    # shard remap, and goodput trend settle before the next decision).
    autoscale_cooldown: int = 3
    # Consecutive out-of-band samples required before acting ("sustained
    # goodput headroom", not one lucky step).
    autoscale_sustain: int = 2
    # Unit-count bounds the policy will never cross.
    autoscale_min: int = 1
    autoscale_max: int = 16
    # --- launcher supervisor (byteps_tpu/launcher.py Supervisor) -----------
    # Max automatic respawns per flapping child before the supervisor
    # gives up on it (ISSUE 20 bounded restart-with-backoff).
    supervisor_restart_limit: int = 3
    # Base respawn delay; doubles per consecutive restart of one child.
    supervisor_backoff_ms: int = 200
    # SIGTERM→SIGKILL escalation grace on retire/shutdown.
    supervisor_grace_ms: int = 2000
    # Supervisor poll cadence (child reap + proc-fault plan tick).
    supervisor_poll_ms: int = 50
    # --- socket NIC (common/socknic.py) ------------------------------------
    # Per-request recv deadline on SocketNicClient (real wire-death
    # classification: past this the request raises TimeoutError).
    socket_timeout_ms: int = 10000
    # Token-bucket shaping for socket NIC payloads (0 = unshaped). The
    # PR 1 DcnPacer, now pacing a real link.
    socket_mbps: float = 0.0
    # Listen-path port probes through server.any_port (the PR 4
    # ephemeral-port-squatter sidestep).
    socket_port_attempts: int = 16

    # --- telemetry plane (docs/observability.md) ---------------------------
    # Always-on metrics registry (common/metrics.py): counters, gauges,
    # fixed-bucket latency/size histograms threaded through every layer
    # (scheduler stages, per-NIC wire, pacer, ICI dispatch, faults,
    # train-step walltime). 0 swaps every handle for a no-op.
    metrics_on: bool = True
    # Flight recorder (common/flight_recorder.py): bounded ring of
    # per-step metric snapshots, dumped on StallError/PartitionFailure.
    # 0 disables the per-step ring (FAULT events still recorded).
    flight_recorder_steps: int = 64
    # Recent FAULT-class events (retries, failovers, evictions,
    # membership changes) kept for the post-mortem; 0 disables.
    flight_recorder_events: int = 128
    # When set: post-mortems are ALSO written as JSON files into this
    # directory (one per distinct failure reason per run); empty = the
    # post-mortem only rides the raised error object.
    flight_recorder_dir: str = ""

    # --- inference serving tier (docs/serving.md) --------------------------
    # KV block size (tokens per paged-cache block). Must divide the
    # model's max_seq for bit-tight packing vs the dense cache (the
    # scheduler validates); 16 suits both the tiny CI configs and the
    # flash kernels' tiling.
    serve_block_size: int = 16
    # Physical KV blocks in the preallocated pool. 0 = auto: enough for
    # max_batch full-length requests plus the reserved scratch block
    # (no oversubscription). Smaller pools oversubscribe and trigger
    # preemption with recompute-on-resume.
    serve_pool_blocks: int = 0
    # Decode-batch slots: how many requests one packed decode step
    # serves (the jitted step's static batch dimension).
    serve_max_batch: int = 8
    # Prefill chunk length in tokens: long prompts are fed through the
    # model this many tokens per scheduler iteration so a 2k-token
    # prompt can't starve the decode lane (Orca-style iteration-level
    # scheduling).
    serve_prefill_chunk: int = 32
    # int8-quantized KV pool (reuses generate.py's _QuantSlot absmax
    # machinery) — ~half the pool HBM of bf16, the knob that doubles
    # the servable batch/context per chip.
    serve_quant_cache: bool = False
    # Default spec_len for per-request speculative policies.
    serve_spec_len: int = 4
    # Radix prefix cache over the paged KV pool (docs/serving.md
    # §prefix cache): committed prefill blocks are published to a
    # content-addressed radix index with per-block refcounts; requests
    # sharing a prompt prefix map their leading table entries to the
    # SAME physical pages (copy-on-write at the divergence block) and
    # skip the shared prefill chunks. Default-on — outputs are pinned
    # bit-identical either way; 0 is the escape hatch.
    serve_prefix_cache: bool = True
    # Replica lease for the serve router (serve/router.py): a replica
    # silent past this many ms (no completed scheduler step) is evicted
    # — epoch bump, its in-flight requests re-queue to survivors.
    # Mirrors the PR 5 server-side worker-lease semantics.
    serve_replica_lease_ms: int = 1000
    # --- disaggregated prefill/decode (docs/serving.md §disaggregation) ----
    # Emulated per-replica KV-migration NIC rate in megabits/s: finished
    # prefill blocks stream to the decode target through a token-bucket
    # pacer at this rate (the PR 1 pacer philosophy — loopback behaves
    # like the wire tier migration actually crosses). 0 = unthrottled.
    serve_disagg_mbps: float = 0.0
    # Admission classification knee: inputs of at least this many tokens
    # route to the prefill tier (when one is armed); shorter prompts
    # prefill in place on their decode replica. Shrinks 4x under decode
    # pool pressure (<= 25% free) — the "prompt length x pool pressure"
    # rule.
    serve_disagg_prompt_threshold: int = 64
    # Migrate-don't-evict: a pool-pressure preemption victim's committed
    # KV blocks move to a sibling replica over the KV wire instead of
    # being freed and recomputed (needs >= 2 decode-capable replicas
    # behind a Router). 0 = classic evict + recompute-on-resume.
    serve_disagg_migrate: bool = True
    # KVCOMPRESS->KVPUSH credits per migration wire: how many encoded
    # blocks may sit between the codec and a throttled wire.
    serve_disagg_credit: int = 4
    # --- multi-tenant LoRA multiplexing (docs/serving.md §multi-tenant) ----
    # Device-resident adapter-pool slots (slot 0 is the reserved
    # all-zero base-model slot, so N slots serve N-1 concurrently-live
    # adapters; idle ones LRU-cache in place). 0 = no pool: the
    # scheduler serves the bare base model and rejects adapter-tagged
    # requests.
    serve_adapter_slots: int = 0
    # Rank bucket every pooled adapter is zero-padded to — mixed-rank
    # tenants share ONE compiled packed decode step (the padding adds
    # exactly 0.0 to the delta; docs/serving.md has the exactness
    # argument). Adapters with rank above the bucket are rejected at
    # registration.
    serve_adapter_rank_bucket: int = 8
    # Per-tenant KV-pool quota in blocks. 0 = off. A tenant's running
    # requests may hold at most this many blocks: growth past it
    # preempts the OFFENDER's own youngest run (never a sibling's),
    # and a single request that could never fit its tenant's quota is
    # rejected at submit — the noisy tenant hits its own wall.
    serve_tenant_quota_blocks: int = 0
    # Deficit-weighted fair queuing at admission: pick the
    # max-credit tenant's oldest eligible request instead of the
    # global head of queue. Single-tenant traffic reduces exactly to
    # the historical FIFO. Off = plain FIFO regardless of tenants.
    serve_fair_queue: bool = True

    # --- tracing (SURVEY §5.1) ---------------------------------------------
    trace_on: bool = False
    trace_dir: str = "./traces"
    trace_start_step: int = 1
    trace_end_step: int = 30
    trace_xprof: bool = False

    # --- auto-tuner (ByteScheduler, SURVEY §2.6) ---------------------------
    auto_tune: bool = False

    # --- TPU-specific knobs (no reference analog; documented in docs/env.md)
    # Name of the data-parallel mesh axis used by push_pull collectives.
    dp_axis: str = "dp"
    # Reduce dtype on the aggregation tier. The reference PS sums in fp32.
    reduce_dtype: str = "float32"
    # Wire transport of the compressed ICI collectives (comm/ici.py):
    # "staged" = one monolithic all_to_all + all_gather (codec and wire
    # serialize); "ring" = the ici-compressed tier — payloads ride n-1
    # ring hops (Pallas make_async_remote_copy kernels on TPU,
    # lax.ppermute twins elsewhere) with per-hop DMA/codec overlap,
    # pinned bit-exact vs staged for deterministic codecs. Under "ring"
    # the hybrid pipeline's REDUCE stage also rides the compressed wire
    # (compressed bytes on ICI) for qualifying partitions.
    ici_tier: str = "staged"

    @classmethod
    def from_env(cls) -> "Config":
        c = cls(
            role=_env_str("DMLC_ROLE", "worker"),
            num_worker=_env_int("DMLC_NUM_WORKER", 1),
            num_server=_env_int("DMLC_NUM_SERVER", 0),
            ps_root_uri=_env_str("DMLC_PS_ROOT_URI", "127.0.0.1"),
            ps_root_port=_env_int("DMLC_PS_ROOT_PORT", 9000),
            worker_id=_env_int("DMLC_WORKER_ID", 0),
            interface=_env_str("DMLC_INTERFACE", ""),
            jax_distributed=_env_bool("BYTEPS_JAX_DISTRIBUTED"),
            jax_coord_uri=_env_str(
                "BYTEPS_JAX_COORD_URI",
                _env_str("DMLC_PS_ROOT_URI", "127.0.0.1"),
            ),
            jax_coord_port=_env_int(
                "BYTEPS_JAX_COORD_PORT", _env_int("DMLC_PS_ROOT_PORT", 9000)
            ),
            local_rank=_env_int("BYTEPS_LOCAL_RANK", 0),
            local_size=_env_int("BYTEPS_LOCAL_SIZE", 1),
            partition_bytes=_env_int("BYTEPS_PARTITION_BYTES", DEFAULT_PARTITION_BYTES),
            scheduling_credit=_env_int("BYTEPS_SCHEDULING_CREDIT", DEFAULT_SCHEDULING_CREDIT),
            force_distributed=_env_bool("BYTEPS_FORCE_DISTRIBUTED"),
            enable_async=_env_bool("BYTEPS_ENABLE_ASYNC"),
            enable_ipc=_env_bool("BYTEPS_ENABLE_IPC"),
            server_engine_threads=_env_int("BYTEPS_SERVER_ENGINE_THREAD", DEFAULT_SERVER_ENGINE_THREADS),
            server_enable_schedule=_env_bool("BYTEPS_SERVER_ENABLE_SCHEDULE"),
            pull_timeout_ms=_env_int("BYTEPS_SERVER_PULL_TIMEOUT_MS", 60000),
            log_level=_env_str("BYTEPS_LOG_LEVEL", "INFO").upper(),
            min_compress_bytes=_env_int("BYTEPS_MIN_COMPRESS_BYTES", 65536),
            dcn_throttle_mbps=_env_float("BYTEPS_DCN_THROTTLE_MBPS", 0.0),
            hybrid_sharded=_env_bool("BYTEPS_HYBRID_SHARDED", True),
            pod_controllers=_env_int("BYTEPS_POD_CONTROLLERS", 1),
            owner_salt=_env_int("BYTEPS_OWNER_SALT", 0),
            num_slices=max(1, _env_int("BYTEPS_NUM_SLICES", 1)),
            zero3=_env_bool("BYTEPS_ZERO3"),
            fault_spec=_env_str("BYTEPS_FAULT_SPEC", ""),
            fault_seed=_env_int("BYTEPS_FAULT_SEED", 0),
            retry_limit=_env_int("BYTEPS_RETRY_LIMIT", 8),
            retry_backoff_ms=_env_int("BYTEPS_RETRY_BACKOFF_MS", 50),
            wire_crc=_env_bool("BYTEPS_WIRE_CRC"),
            health_interval_ms=_env_int("BYTEPS_HEALTH_INTERVAL_MS", 0),
            health_miss_limit=_env_int("BYTEPS_HEALTH_MISS_LIMIT", 3),
            degraded_ok=_env_bool("BYTEPS_DEGRADED_OK", True),
            worker_lease_ms=_env_int("BYTEPS_WORKER_LEASE_MS", 0),
            handle_deadline_ms=_env_int("BYTEPS_HANDLE_DEADLINE_MS", 0),
            staleness=max(0, _env_int("BYTEPS_STALENESS", 0)),
            autoscale_hysteresis=_env_float("BYTEPS_AUTOSCALE_HYSTERESIS",
                                            0.1),
            autoscale_cooldown=_env_int("BYTEPS_AUTOSCALE_COOLDOWN", 3),
            autoscale_sustain=_env_int("BYTEPS_AUTOSCALE_SUSTAIN", 2),
            autoscale_min=_env_int("BYTEPS_AUTOSCALE_MIN", 1),
            autoscale_max=_env_int("BYTEPS_AUTOSCALE_MAX", 16),
            supervisor_restart_limit=_env_int(
                "BYTEPS_SUPERVISOR_RESTART_LIMIT", 3),
            supervisor_backoff_ms=_env_int(
                "BYTEPS_SUPERVISOR_BACKOFF_MS", 200),
            supervisor_grace_ms=_env_int(
                "BYTEPS_SUPERVISOR_GRACE_MS", 2000),
            supervisor_poll_ms=_env_int("BYTEPS_SUPERVISOR_POLL_MS", 50),
            socket_timeout_ms=_env_int("BYTEPS_SOCKET_TIMEOUT_MS", 10000),
            socket_mbps=_env_float("BYTEPS_SOCKET_MBPS", 0.0),
            socket_port_attempts=_env_int("BYTEPS_SOCKET_PORT_ATTEMPTS",
                                          16),
            metrics_on=_env_bool("BYTEPS_METRICS_ON", True),
            flight_recorder_steps=_env_int("BYTEPS_FLIGHT_RECORDER_STEPS",
                                           64),
            flight_recorder_events=_env_int("BYTEPS_FLIGHT_RECORDER_EVENTS",
                                            128),
            flight_recorder_dir=_env_str("BYTEPS_FLIGHT_RECORDER_DIR", ""),
            serve_block_size=_env_int("BYTEPS_SERVE_BLOCK_SIZE", 16),
            serve_pool_blocks=_env_int("BYTEPS_SERVE_POOL_BLOCKS", 0),
            serve_max_batch=_env_int("BYTEPS_SERVE_MAX_BATCH", 8),
            serve_prefill_chunk=_env_int("BYTEPS_SERVE_PREFILL_CHUNK", 32),
            serve_quant_cache=_env_bool("BYTEPS_SERVE_QUANT_CACHE"),
            serve_spec_len=_env_int("BYTEPS_SERVE_SPEC_LEN", 4),
            serve_prefix_cache=_env_bool("BYTEPS_SERVE_PREFIX_CACHE",
                                         True),
            serve_replica_lease_ms=_env_int(
                "BYTEPS_SERVE_REPLICA_LEASE_MS", 1000),
            serve_disagg_mbps=_env_float("BYTEPS_SERVE_DISAGG_MBPS", 0.0),
            serve_disagg_prompt_threshold=_env_int(
                "BYTEPS_SERVE_DISAGG_PROMPT_THRESHOLD", 64),
            serve_disagg_migrate=_env_bool("BYTEPS_SERVE_DISAGG_MIGRATE",
                                           True),
            serve_disagg_credit=_env_int("BYTEPS_SERVE_DISAGG_CREDIT", 4),
            serve_adapter_slots=_env_int("BYTEPS_SERVE_ADAPTER_SLOTS", 0),
            serve_adapter_rank_bucket=_env_int(
                "BYTEPS_SERVE_ADAPTER_RANK_BUCKET", 8),
            serve_tenant_quota_blocks=_env_int(
                "BYTEPS_SERVE_TENANT_QUOTA_BLOCKS", 0),
            serve_fair_queue=_env_bool("BYTEPS_SERVE_FAIR_QUEUE", True),
            trace_on=_env_bool("BYTEPS_TRACE_ON"),
            trace_dir=_env_str("BYTEPS_TRACE_DIR", "./traces"),
            trace_start_step=_env_int("BYTEPS_TRACE_START_STEP", 1),
            trace_end_step=_env_int("BYTEPS_TRACE_END_STEP", 30),
            trace_xprof=_env_bool("BYTEPS_TRACE_XPROF"),
            auto_tune=_env_bool("BYTEPS_AUTO_TUNE"),
            dp_axis=_env_str("BYTEPS_DP_AXIS", "dp"),
            reduce_dtype=_env_str("BYTEPS_REDUCE_DTYPE", "float32"),
            ici_tier=_env_str("BYTEPS_ICI_TIER", "staged"),
        )
        return c

    def snapshot(self) -> dict:
        """JSON-safe dict of every resolved knob. Stamped into chrome-
        trace metadata (``TraceRecorder.dump``) and flight-recorder
        post-mortems so a recorded run carries the configuration that
        produced it — the what-if simulator (``byteps_tpu/sim``) replays
        a run from its artifacts alone, no out-of-band knowledge."""
        return dataclasses.asdict(self)

    @property
    def is_distributed(self) -> bool:
        """Multi-host via the DCN PS tier vs collectives-only.

        Mirrors the reference's distinction between the NCCL-only single
        machine fast path and the hybrid-PS distributed path
        (``byteps/common/operations.cc`` queue-list construction). In
        global-mesh mode (``BYTEPS_JAX_DISTRIBUTED``) multi-worker jobs are
        collectives-only: one mesh spans the hosts and psum crosses DCN,
        so the PS tier stays out of the picture.
        """
        if self.jax_distributed:
            return self.force_distributed
        return self.num_worker > 1 or self.force_distributed


_config: Optional[Config] = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config.from_env()
    return _config


def set_config(cfg: Config) -> None:
    global _config
    _config = cfg


def reset_config() -> None:
    """Drop the cached config (tests mutate env vars)."""
    global _config
    _config = None
