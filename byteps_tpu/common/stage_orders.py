"""Canonical pipeline stage-name orders — a LIGHT leaf module.

Declared here, away from the pipelines themselves, for exactly one
reason: ``trace_analysis`` must be usable on an analysis-only box (a
copied trace file, no jax installed), and importing the pipeline
modules to learn their stage names would drag in the whole data plane
(dcn_adapter → compression → jax). The pipelines stay the enforcement
point — DcnCore and the jax adapter ``bps_check`` their BUILT stage
lists against these constants at construction, and every
``PipelineScheduler`` re-registers its live stage list — so a stage
added to a constructor without updating its constant raises, instead
of silently drifting (the PR 4 ALLGATHER problem this replaces).

Importing this module registers every order into the scheduler's
stage-order registry (worker pipelines first, server rows after).
"""

from __future__ import annotations

from byteps_tpu.common.scheduler import register_stage_order

# Host-adapter DCN pipeline (DcnCore) — reference core_loops.cc order.
DCN_STAGE_ORDER = ("COMPRESS", "PUSH", "PULL", "DECOMPRESS")
# Jax hybrid pipeline (reference root-GPU queue list); unsharded mode
# runs the same order without the ALLGATHER tail.
HYBRID_STAGE_ORDER = (("REDUCE", "COPYD2H") + DCN_STAGE_ORDER
                      + ("COPYH2D", "ALLGATHER"))
# Jax eager ICI pipeline.
EAGER_STAGE_ORDER = ("PUSHPULL", "SYNC")
# Per-key rows the C++ summation server's own chrome trace emits.
SERVER_STAGE_ORDER = ("PUSH_RECV", "SUM", "PULL_RESP", "ROUND")

register_stage_order(HYBRID_STAGE_ORDER)
register_stage_order(DCN_STAGE_ORDER)
register_stage_order(EAGER_STAGE_ORDER)
register_stage_order(SERVER_STAGE_ORDER)
