"""Bridge jax API renames so one codebase runs on old and new jax.

The package is written against the current public names
(``jax.shard_map`` with ``check_vma``, ``jax.typeof``, ``jax.lax.pvary`` /
``pcast`` / ``axis_size``); older jax ships the same functionality as
``jax.experimental.shard_map`` (``check_rep``), ``jax.core.get_aval``,
and psum-of-1. ``ensure()`` aliases forward — never monkeypatching
behavior, only names — which keeps an image's baked-in older jax usable
without a pip install (the no-new-deps constraint).

Called from the jax-consuming subpackage ``__init__``s (comm, jax, ops,
models, parallel), NOT from the top-level package import: jax-less hosts
(a standalone DCN server box, a torch-only worker) must import
``byteps_tpu``/``byteps_tpu.server`` without paying for — or even
having — jax.
"""

from __future__ import annotations

_installed = False


def ensure() -> None:
    """Install the name aliases once per process; no-op on current jax."""
    global _installed
    if _installed:
        return
    _installed = True
    import jax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kw):
            # check_rep=False always: old jax's replication inference is
            # strictly weaker than the VMA system this codebase is
            # written against (it cannot see through psum-of-masked
            # patterns the train steps use), so check_vma=True callers
            # would spuriously fail; numerics stay pinned by the tests
            kw.setdefault("check_rep", False)
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

        jax.shard_map = shard_map
    if not hasattr(jax, "typeof"):
        # jax.typeof returns the aval; pre-rename avals lack ``.vma``,
        # which every caller here already guards with getattr/try
        jax.typeof = jax.core.get_aval
    if not hasattr(jax.lax, "axis_size"):
        # psum of a concrete 1 over a named axis constant-folds to the
        # static axis size — the documented pre-axis_size spelling
        jax.lax.axis_size = lambda name: jax.lax.psum(1, name)
    if not hasattr(jax.lax, "pvary"):
        # pvary/pcast only adjust the VMA *type*, never values; pre-VMA
        # jax has no such type, so the identity is the exact semantics
        jax.lax.pvary = lambda x, axes=(): x
    if not hasattr(jax.lax, "pcast"):
        jax.lax.pcast = lambda x, axes=(), to=None: x
