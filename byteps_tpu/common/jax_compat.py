"""Bridge jax API renames so one codebase runs on old and new jax.

The package is written against the current public names
(``jax.shard_map`` with ``check_vma``, ``jax.typeof``, ``jax.lax.pvary`` /
``pcast`` / ``axis_size``); older jax ships the same functionality as
``jax.experimental.shard_map`` (``check_rep``), ``jax.core.get_aval``,
and psum-of-1. ``ensure()`` aliases forward — never monkeypatching
behavior, only names — which keeps an image's baked-in older jax usable
without a pip install (the no-new-deps constraint).

Called from the jax-consuming subpackage ``__init__``s (comm, jax, ops,
models, parallel), NOT from the top-level package import: jax-less hosts
(a standalone DCN server box, a torch-only worker) must import
``byteps_tpu``/``byteps_tpu.server`` without paying for — or even
having — jax.
"""

from __future__ import annotations

_installed = False


def native_vma() -> bool:
    """True when this jax ships the real VMA type system (native
    ``jax.shard_map`` with ``check_vma``), False when :func:`ensure` is
    bridging the old ``jax.experimental.shard_map``/``check_rep`` API.

    The distinction matters for AD through in-shard_map collectives:
    under real VMA, ``psum`` of a varying value yields an INVARIANT type
    whose transpose seeds ONE cotangent; pre-VMA jax transposes psum to
    psum, so grads of a psum'd replicated objective come out n× (the
    train factories' explicit no-VMA grad assembly compensates — see
    models/train.py — but tests pinning the VMA-typed property itself
    must skip here)."""
    import inspect

    import jax

    if getattr(ensure, "_bridged", False) or not hasattr(jax, "shard_map"):
        return False
    try:
        # a top-level shard_map WITHOUT the check_vma parameter is the
        # pre-VMA export band — same psum-to-psum transpose as old jax
        return "check_vma" in inspect.signature(jax.shard_map).parameters
    except (TypeError, ValueError):
        return False


def ensure() -> None:
    """Install the name aliases once per process; no-op on current jax."""
    global _installed
    if _installed:
        return
    _installed = True
    import jax

    if not hasattr(jax, "shard_map"):
        ensure._bridged = True      # pre-VMA jax (see native_vma)
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kw):
            # check_rep=False always: old jax's replication inference is
            # strictly weaker than the VMA system this codebase is
            # written against (it cannot see through psum-of-masked
            # patterns the train steps use), so check_vma=True callers
            # would spuriously fail; numerics stay pinned by the tests
            kw.setdefault("check_rep", False)
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

        jax.shard_map = shard_map
    if not hasattr(jax, "typeof"):
        # jax.typeof returns the aval; pre-rename avals lack ``.vma``,
        # which every caller here already guards with getattr/try
        jax.typeof = jax.core.get_aval
    if not hasattr(jax.lax, "axis_size"):
        # psum of a concrete 1 over a named axis constant-folds to the
        # static axis size — the documented pre-axis_size spelling
        jax.lax.axis_size = lambda name: jax.lax.psum(1, name)
    if not hasattr(jax.lax, "pvary"):
        # pvary/pcast only adjust the VMA *type*, never values; pre-VMA
        # jax has no such type, so the identity is the exact semantics
        jax.lax.pvary = lambda x, axes=(): x
    if not hasattr(jax.lax, "pcast"):
        jax.lax.pcast = lambda x, axes=(), to=None: x
