"""Core runtime: config, logging, tracing, partitioning, scheduling.

TPU-native equivalent of the reference's ``byteps/common/`` C++ layer
(``global.cc``, ``operations.cc``, ``core_loops.cc``, ``scheduled_queue.cc``).
On TPU there is one process per host (not per device), so the reference's
unix-socket intra-node control plane (``communicator.cc``) collapses into
in-process data structures, and NCCL management (``nccl_manager.cc``) is
replaced by XLA collectives over the ICI mesh.
"""

from byteps_tpu.common.config import Config, get_config, reset_config  # noqa: F401
from byteps_tpu.common.logging import get_logger  # noqa: F401
from byteps_tpu.common.tracing import TraceRecorder, get_tracer  # noqa: F401
