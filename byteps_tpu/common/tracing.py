"""Chrome trace-event recorder (SURVEY §5.1).

The reference collects per-tensor, per-queue-stage timestamps in its core
loops and dumps Chrome trace-event JSON per worker, controlled by
``BYTEPS_TRACE_ON`` / ``BYTEPS_TRACE_DIR`` / ``BYTEPS_TRACE_START_STEP`` /
``BYTEPS_TRACE_END_STEP`` (reference ``docs/timeline.md``; the joapolarbear
fork exists largely to feed these traces to dPRO). We reproduce the same
schema: one ``X`` (complete) event per partition per pipeline stage, with
``pid`` = worker rank, ``tid`` = stage name, and args carrying key/partition
metadata, so dPRO-style per-stage attribution works on the TPU build.

Device-side work is additionally coverable by ``jax.profiler`` XLA traces;
this recorder is the framework-level (scheduler/transport) view.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from byteps_tpu.common.config import get_config
from byteps_tpu.common.logging import get_logger

log = get_logger("tracing")


class TraceRecorder:
    """Collects chrome trace events; thread-safe; dumps per-worker JSON."""

    def __init__(
        self,
        enabled: bool = False,
        trace_dir: str = "./traces",
        start_step: int = 1,
        end_step: int = 30,
        rank: int = 0,
    ) -> None:
        self.enabled = enabled
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.end_step = end_step
        self.rank = rank
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._step = 0
        self._origin = time.perf_counter_ns()
        self._dumped = False

    # -- step lifecycle -----------------------------------------------------
    def step(self) -> None:
        """Advance the step counter; auto-dump once past end_step."""
        self._step += 1
        if self.enabled and self._step > self.end_step:
            self.dump()

    @property
    def active(self) -> bool:
        return (
            self.enabled
            and self.start_step <= self._step <= self.end_step
        )

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._origin) / 1e3

    # -- event emission -----------------------------------------------------
    def complete_event(
        self,
        name: str,
        stage: str,
        start_us: float,
        dur_us: float,
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        if not self.active:
            return
        ev = {
            "name": name,
            "cat": "byteps",
            "ph": "X",
            "ts": start_us,
            "dur": dur_us,
            "pid": self.rank,
            "tid": stage,
            "args": args or {},
        }
        with self._lock:
            self._events.append(ev)

    def span(self, name: str, stage: str, args: Optional[Dict[str, Any]] = None):
        """Context manager emitting one complete event."""
        return _Span(self, name, stage, args)

    def instant(self, name: str, stage: str, args: Optional[Dict[str, Any]] = None) -> None:
        if not self.active:
            return
        ev = {
            "name": name,
            "cat": "byteps",
            "ph": "i",
            "ts": self._now_us(),
            "s": "t",
            "pid": self.rank,
            "tid": stage,
            "args": args or {},
        }
        with self._lock:
            self._events.append(ev)

    # -- output -------------------------------------------------------------
    def dump(self, path: Optional[str] = None) -> Optional[str]:
        if self._dumped or not self._events:
            return None
        self._dumped = True
        if path is None:
            os.makedirs(self.trace_dir, exist_ok=True)
            path = os.path.join(self.trace_dir, f"trace_rank{self.rank}.json")
        with self._lock:
            doc = {
                "traceEvents": self._events,
                "displayTimeUnit": "ms",
                "metadata": {"rank": self.rank, "framework": "byteps_tpu"},
            }
        with open(path, "w") as f:
            json.dump(doc, f)
        log.info("dumped %d trace events to %s", len(self._events), path)
        return path


class _Span:
    def __init__(self, rec: TraceRecorder, name: str, stage: str, args):
        self.rec = rec
        self.name = name
        self.stage = stage
        self.args = args
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = self.rec._now_us()
        return self

    def __exit__(self, *exc):
        self.rec.complete_event(
            self.name, self.stage, self.t0, self.rec._now_us() - self.t0, self.args
        )
        return False


_tracer: Optional[TraceRecorder] = None


def get_tracer() -> TraceRecorder:
    global _tracer
    if _tracer is None:
        cfg = get_config()
        _tracer = TraceRecorder(
            enabled=cfg.trace_on,
            trace_dir=cfg.trace_dir,
            start_step=cfg.trace_start_step,
            end_step=cfg.trace_end_step,
            rank=cfg.worker_id,
        )
    return _tracer


def reset_tracer() -> None:
    global _tracer
    _tracer = None
