"""Chrome trace-event recorder (SURVEY §5.1).

The reference collects per-tensor, per-queue-stage timestamps in its core
loops and dumps Chrome trace-event JSON per worker, controlled by
``BYTEPS_TRACE_ON`` / ``BYTEPS_TRACE_DIR`` / ``BYTEPS_TRACE_START_STEP`` /
``BYTEPS_TRACE_END_STEP`` (reference ``docs/timeline.md``; the joapolarbear
fork exists largely to feed these traces to dPRO). We reproduce the same
schema: one ``X`` (complete) event per partition per pipeline stage, with
``pid`` = worker rank, ``tid`` = stage name, and args carrying key/partition
metadata, so dPRO-style per-stage attribution works on the TPU build.

Device-side work is additionally coverable by ``jax.profiler`` XLA traces;
this recorder is the framework-level (scheduler/transport) view.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from byteps_tpu.common.config import get_config
from byteps_tpu.common.flight_recorder import get_flight_recorder
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import json_safe

log = get_logger("tracing")


class TraceRecorder:
    """Collects chrome trace events; thread-safe; dumps per-worker JSON."""

    def __init__(
        self,
        enabled: bool = False,
        trace_dir: str = "./traces",
        start_step: int = 1,
        end_step: int = 30,
        rank: int = 0,
        xprof: bool = False,
    ) -> None:
        self.enabled = enabled
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.end_step = end_step
        self.rank = rank
        # BYTEPS_TRACE_XPROF=1: capture a jax.profiler (XLA/xprof) trace
        # over the SAME [start_step, end_step] window as the chrome
        # trace — device-side kernel/fusion attribution beside the
        # framework's stage spans (view with tensorboard or xprof)
        self.xprof = xprof and enabled
        self._xprof_running = False
        self.metadata: Dict[str, Any] = {}
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._step = 0
        # Timestamps are ABSOLUTE epoch microseconds, advanced by the
        # monotonic clock (immune to wall-clock steps mid-run): the server
        # trace records CLOCK_REALTIME, so worker and server events land on
        # one timeline without post-hoc shifting (same host; cross-host uses
        # the recorded ping clock offset — see merge_traces).
        self._epoch0_ns = time.time_ns()
        self._perf0_ns = time.perf_counter_ns()
        self._dumped = False

    # -- step lifecycle -----------------------------------------------------
    def step(self) -> None:
        """Advance the step counter; auto-dump once past end_step."""
        self._step += 1
        # ALWAYS-ON step boundary: the flight recorder snapshots the
        # metrics registry per step regardless of trace_on — step
        # advancement is the one signal every aggregation path already
        # drives (docs/observability.md)
        get_flight_recorder().on_step(self._step)
        self._maybe_xprof()
        if self.enabled and self._step > self.end_step:
            self.dump()

    def advance_to(self, step_no: int) -> None:
        """Idempotent step advance: the production paths drive this
        automatically (eager: a tensor's round/version number; fused: the
        optimizer's count via jax.debug.callback), so ``BYTEPS_TRACE_ON=1``
        alone records — no manual ``step()`` calls in user code."""
        dump = False
        with self._lock:
            if step_no <= self._step:
                return
            self._step = step_no
            dump = self.enabled and self._step > self.end_step
        get_flight_recorder().on_step(step_no)
        self._maybe_xprof()
        if dump:
            self.dump()

    def _maybe_xprof(self) -> None:
        """Start/stop the jax.profiler capture at the window edges.
        Failures degrade to a warning — the chrome trace still records."""
        if not self.xprof:
            return
        entering = (not self._xprof_running
                    and self.start_step <= self._step <= self.end_step)
        leaving = self._xprof_running and self._step > self.end_step
        if not entering and not leaving:
            return
        try:
            import jax

            if entering:
                d = os.path.join(self.trace_dir, f"xprof_rank{self.rank}")
                os.makedirs(d, exist_ok=True)
                jax.profiler.start_trace(d)
                self._xprof_running = True
                log.info("xprof capture started -> %s", d)
            else:
                jax.profiler.stop_trace()
                self._xprof_running = False
                log.info("xprof capture stopped")
        except Exception as e:  # noqa: BLE001 — profiler support varies
            self.xprof = False
            self._xprof_running = False
            log.warning("xprof capture unavailable: %s", e)

    def fused_step(self, count: int, args: Optional[Dict[str, Any]] = None) -> None:
        """Per-execution marker fired from inside a jitted train step
        (``jax.debug.callback`` in ``DistributedOptimizer.update``); `count`
        is the optimizer's pre-increment step counter. Idempotent across
        the per-shard duplicate callbacks shard_map can produce."""
        step_no = int(count) + 1
        emit = False
        with self._lock:
            if step_no > self._step:
                self._step = step_no
                emit = True
        if emit:
            get_flight_recorder().on_step(step_no)
            self._maybe_xprof()
            self.instant(f"step{step_no}", "FUSED_PUSHPULL", args)
            if self.enabled and self._step > self.end_step:
                self.dump()

    def host_step(self, args: Optional[Dict[str, Any]] = None) -> None:
        """Host-side per-call step marker — the fallback for backends
        whose PJRT plugin rejects host callbacks (the axon TPU tunnel),
        where the fused path's in-program ``jax.debug.callback`` marker
        cannot run. Fired by the train-step wrapper installed in
        ``models/train.py _finalize_step``; advances the window by one
        per dispatched step (dispatch-time, not completion-time — step
        numbering for the [start, end] window, not a latency probe)."""
        self.fused_step(self._step, args or {"marker": "host"})

    @property
    def active(self) -> bool:
        return (
            self.enabled
            and self.start_step <= self._step <= self.end_step
        )

    def _now_us(self) -> float:
        return (
            self._epoch0_ns + (time.perf_counter_ns() - self._perf0_ns)
        ) / 1e3

    # -- event emission -----------------------------------------------------
    def complete_event(
        self,
        name: str,
        stage: str,
        start_us: float,
        dur_us: float,
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        if not self.active:
            return
        ev = {
            "name": name,
            "cat": "byteps",
            "ph": "X",
            "ts": start_us,
            "dur": dur_us,
            "pid": self.rank,
            "tid": stage,
            # sanitize at the producer boundary: ONE rule for every call
            # site (np.bool_/np-scalar args broke the JSON dump once —
            # see metrics.json_safe)
            "args": json_safe(args or {}),
        }
        with self._lock:
            self._events.append(ev)

    def span(self, name: str, stage: str, args: Optional[Dict[str, Any]] = None):
        """Context manager emitting one complete event."""
        return _Span(self, name, stage, args)

    def instant(self, name: str, stage: str, args: Optional[Dict[str, Any]] = None) -> None:
        if stage == "FAULT":
            # every FAULT-track instant (retries, failovers, evictions,
            # membership, injections) also lands in the ALWAYS-ON flight
            # recorder — the chrome trace is the opt-in consumer, the
            # post-mortem ring the unconditional one
            get_flight_recorder().record_event(name, args)
        if not self.active:
            return
        ev = {
            "name": name,
            "cat": "byteps",
            "ph": "i",
            "ts": self._now_us(),
            "s": "t",
            "pid": self.rank,
            "tid": stage,
            "args": json_safe(args or {}),
        }
        with self._lock:
            self._events.append(ev)

    # -- output -------------------------------------------------------------
    def dump(self, path: Optional[str] = None) -> Optional[str]:
        if self._xprof_running:
            # run ended inside the window — close the device capture
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                log.warning("xprof stop at dump failed: %s", e)
            self._xprof_running = False
        if self._dumped or not self._events:
            return None
        self._dumped = True
        if path is None:
            os.makedirs(self.trace_dir, exist_ok=True)
            path = os.path.join(self.trace_dir, f"trace_rank{self.rank}.json")
        with self._lock:
            doc = {
                "traceEvents": self._events,
                "displayTimeUnit": "ms",
                "metadata": json_safe({
                    "rank": self.rank,
                    "framework": "byteps_tpu",
                    "clock": "epoch_us",
                    # the run's resolved knobs: a dumped trace is
                    # replayable by the what-if simulator without
                    # out-of-band knowledge (sim/extract.py)
                    "config": get_config().snapshot(),
                    **self.metadata,
                }),
            }
        with open(path, "w") as f:
            json.dump(doc, f)
        log.info("dumped %d trace events to %s", len(self._events), path)
        return path


class _Span:
    def __init__(self, rec: TraceRecorder, name: str, stage: str, args):
        self.rec = rec
        self.name = name
        self.stage = stage
        self.args = args
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = self.rec._now_us()
        return self

    def __exit__(self, *exc):
        self.rec.complete_event(
            self.name, self.stage, self.t0, self.rec._now_us() - self.t0, self.args
        )
        return False


_tracer: Optional[TraceRecorder] = None


def get_tracer() -> TraceRecorder:
    global _tracer
    if _tracer is None:
        cfg = get_config()
        _tracer = TraceRecorder(
            enabled=cfg.trace_on,
            trace_dir=cfg.trace_dir,
            start_step=cfg.trace_start_step,
            end_step=cfg.trace_end_step,
            rank=cfg.worker_id,
            xprof=cfg.trace_xprof,
        )
    return _tracer


def reset_tracer() -> None:
    global _tracer
    _tracer = None


def merge_traces(out_path: str, in_paths: List[str]) -> int:
    """Merge per-role chrome traces onto ONE aligned timeline.

    Worker traces carry absolute epoch-us timestamps; server traces carry
    CLOCK_REALTIME us (the same clock on the same host). For a server on a
    DIFFERENT host, the worker that pinged it recorded
    ``server_clock_offset_ns`` (= server_clock − worker_clock, kPing RTT/2
    method — SURVEY §5.1, the dPRO cross-worker alignment capability) in
    its own metadata; server events are shifted by −offset onto the
    workers' clock here. Returns the merged event count.
    """
    docs = [json.load(open(p)) for p in in_paths]
    # per-server offsets (server_clock − worker_clock, ns) from the first
    # worker that probed them; every server's rows get their OWN shift
    offsets_ns: Dict[str, float] = {}
    for d in docs:
        md = d.get("metadata", {})
        if md.get("role") != "server" and md.get("server_clock_offsets"):
            offsets_ns = {
                str(k): float(v)
                for k, v in md["server_clock_offsets"].items()
            }
            break
    events: List[Dict[str, Any]] = []
    for d in docs:
        md = d.get("metadata", {})
        is_server = md.get("role") == "server"
        offset_us = (
            offsets_ns.get(str(md.get("server_id", 0)), 0.0) / 1e3
            if is_server else 0.0
        )
        for ev in d.get("traceEvents", []):
            if is_server and offset_us:
                ev = {**ev, "ts": ev["ts"] - offset_us}
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0))
    with open(out_path, "w") as f:
        json.dump(
            {
                "traceEvents": events,
                "displayTimeUnit": "ms",
                "metadata": {"merged_from": [os.path.basename(p) for p in in_paths]},
            },
            f,
        )
    return len(events)


def _merge_main(argv: List[str]) -> int:
    """CLI: python -m byteps_tpu.common.tracing merged.json trace1.json ..."""
    if len(argv) < 3:
        print("usage: python -m byteps_tpu.common.tracing OUT.json IN.json "
              "[IN.json ...]")
        return 2
    n = merge_traces(argv[1], argv[2:])
    print(f"merged {n} events from {len(argv) - 2} traces into {argv[1]}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_merge_main(sys.argv))
