"""Logging — equivalent of the reference's ``BPS_LOG`` / ``BPS_CHECK``
macros (``byteps/common/logging.{h,cc}``), honoring ``BYTEPS_LOG_LEVEL``
(trace/debug/info/warning/fatal).
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "TRACE": 5,
    "DEBUG": logging.DEBUG,
    "INFO": logging.INFO,
    "WARNING": logging.WARNING,
    "ERROR": logging.ERROR,
    "FATAL": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    # Config.log_level is the source of truth (itself fed by
    # BYTEPS_LOG_LEVEL); fall back to the raw env var if config import
    # is not possible yet.
    try:
        from byteps_tpu.common.config import get_config

        level_name = get_config().log_level
    except Exception:
        level_name = os.environ.get("BYTEPS_LOG_LEVEL", "INFO").upper()
    level = _LEVELS.get(level_name, logging.INFO)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("[%(asctime)s] %(name)s %(levelname)s: %(message)s")
    )
    root = logging.getLogger("byteps_tpu")
    root.setLevel(level)
    root.addHandler(handler)
    root.propagate = False
    _configured = True


def get_logger(name: str = "byteps_tpu") -> logging.Logger:
    _configure_root()
    if not name.startswith("byteps_tpu"):
        name = "byteps_tpu." + name
    return logging.getLogger(name)


def bps_check(cond: bool, msg: str = "") -> None:
    """``BPS_CHECK``-style invariant assertion."""
    if not cond:
        raise RuntimeError(f"BPS_CHECK failed: {msg}")
