"""Online auto-tuner for (partition_bytes, scheduling_credit).

Reference analog: the ByteScheduler subproject's Bayesian search
(``bytescheduler/bytescheduler/common/search.py`` tuning credit/partition
size online during training, SOSP'19 §5; SURVEY §2.6 notes the rebuild
needs ONE scheduler but should reproduce the tuner).

Strategy: coordinate-descent hill climbing over a small log-spaced grid —
measure the median step time of the current config over ``interval`` steps,
try a neighbor along one knob, keep it if faster by ``min_gain`` else
revert and switch knobs. Simpler than the reference's Bayesian optimizer
but converges on the same two-knob space in tens of steps and has no
dependencies. (On the fused jit path a partition-bytes move triggers one
retrace per new value; the grid is small so compiles are cached.)

With a ``proposer`` attached (``byteps_tpu.sim.search.make_proposer`` —
the dPRO-style what-if simulator, docs/whatif.md), the tuner stops
exploring neighbors blind: after each measurement window it asks the
proposer for the next candidate (the simulator's predicted-fastest
configs it has not yet measured) and converges the moment the proposer
runs dry — live evaluations are spent CONFIRMING a simulated shortlist
instead of walking the grid. No trace/proposer ⇒ the grid walk above,
unchanged.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Callable, List, Optional, Tuple

from byteps_tpu.common.logging import get_logger

log = get_logger("tuner")

PARTITION_GRID = [512 << 10, 1 << 20, 2 << 20, 4 << 20, 8 << 20, 16 << 20]
CREDIT_GRID = [2, 4, 8, 16, 32]


@dataclasses.dataclass
class _Candidate:
    part_idx: int
    credit_idx: int

    @property
    def partition_bytes(self) -> int:
        return PARTITION_GRID[self.part_idx]

    @property
    def credit(self) -> int:
        return CREDIT_GRID[self.credit_idx]


class AutoTuner:
    """Feed ``record_step(seconds)`` once per training step; the tuner calls
    ``apply(partition_bytes, credit)`` whenever it moves.

    ``apply`` is typically ``lambda pb, cr: (registry.repartition(pb),
    scheduler.set_credit(cr))`` for the eager path, or a closure that sets
    the partition_bytes used at the next jit trace for the fused path.
    """

    def __init__(
        self,
        apply: Callable[[int, int], None],
        interval: int = 5,
        warmup: int = 3,
        min_gain: float = 0.02,
        partition_bytes: int = 4 << 20,
        credit: int = 4,
        knobs: Tuple[str, ...] = ("partition", "credit"),
        proposer: Optional[Callable[
            [Tuple[int, int], Optional[float], dict],
            Optional[Tuple[int, int]]]] = None,
    ) -> None:
        """``knobs`` restricts the search space: the fused jit path has no
        credit scheduler (XLA owns overlap), so it tunes ``("partition",)``
        only — every move there costs a retrace, and burning evaluations on
        a knob with no effect would double convergence time.

        ``proposer(best_cfg, best_time, measured) -> (pb, cr) | None``
        replaces neighbor exploration with an externally ranked
        candidate stream (the what-if simulator's shortlist —
        ``sim.search.make_proposer``): ``measured`` maps every
        (partition_bytes, credit) already evaluated to its best median,
        and ``None`` means the stream is exhausted — the tuner then
        converges on its measured best. Off-grid proposals snap to the
        grids (the simulator's own grids match, so this is a no-op in
        practice)."""
        _KNOBS = ("partition", "credit")
        bad = [k for k in knobs if k not in _KNOBS]
        if bad or not knobs:
            raise ValueError(f"knobs must be a non-empty subset of {_KNOBS}")
        pi = min(range(len(PARTITION_GRID)),
                 key=lambda i: abs(PARTITION_GRID[i] - partition_bytes))
        ci = min(range(len(CREDIT_GRID)),
                 key=lambda i: abs(CREDIT_GRID[i] - credit))
        if (PARTITION_GRID[pi], CREDIT_GRID[ci]) != (partition_bytes, credit):
            log.info(
                "tuner: snapping start config to grid: partition %d→%d "
                "bytes, credit %d→%d", partition_bytes, PARTITION_GRID[pi],
                credit, CREDIT_GRID[ci],
            )
        self._apply = apply
        self._interval = max(2, interval)
        self._warmup = warmup
        self._min_gain = min_gain
        self._current = _Candidate(pi, ci)
        self._best = self._current
        self._best_time: Optional[float] = None
        self._samples: List[float] = []
        self._steps = 0
        self._knobs = tuple(knobs)
        self._knob_i = 0
        self._direction = +1
        self._exhausted = 0     # directions tried without improvement
        self._proposer = proposer
        # (partition_bytes, credit) -> best median measured there; what
        # the proposer consults to skip already-evaluated configs
        self.measured: dict = {}
        self.converged = False
        self._apply(self._current.partition_bytes, self._current.credit)

    # -- measurement --------------------------------------------------------
    def record_step(self, seconds: float) -> None:
        if self.converged:
            return
        self._steps += 1
        if self._steps <= self._warmup:
            return  # compile/cache effects pollute early samples
        self._samples.append(seconds)
        if len(self._samples) >= self._interval:
            self._evaluate(statistics.median(self._samples))
            self._samples.clear()
            self._steps = 0

    # -- hill climbing ------------------------------------------------------
    def _evaluate(self, t: float) -> None:
        key = (self._current.partition_bytes, self._current.credit)
        prev = self.measured.get(key)
        self.measured[key] = t if prev is None else min(prev, t)
        if self._best_time is None or t < self._best_time * (1 - self._min_gain):
            if self._best_time is not None:
                log.info(
                    "tuner: kept partition=%dKB credit=%d (%.1fms < %.1fms)",
                    self._current.partition_bytes >> 10, self._current.credit,
                    t * 1e3, self._best_time * 1e3,
                )
                self._exhausted = 0
            self._best = self._current
            self._best_time = t
        else:
            # revert and rotate direction/knob
            self._current = self._best
            self._exhausted += 1
            self._rotate()
        if self._proposer is not None:
            self._propose_next()
            return
        # Find the next candidate, skipping grid-edge dead ends WITHOUT
        # spending a measurement on them: starting at the top of the grid,
        # the +1 direction is exhausted for free and the -1 neighbor still
        # gets its fair evaluation before convergence can fire.
        while True:
            if self._exhausted >= 2 * len(self._knobs):
                self.converged = True
                self._apply(self._best.partition_bytes, self._best.credit)
                log.info("tuner converged: partition=%dKB credit=%d",
                         self._best.partition_bytes >> 10, self._best.credit)
                return
            nxt = self._neighbor()
            if nxt is not None:
                break
            self._exhausted += 1
            self._rotate()
        self._current = nxt
        self._apply(self._current.partition_bytes, self._current.credit)

    def _propose_next(self) -> None:
        """Simulator-guided move: ask the proposer for the next
        unmeasured candidate; an exhausted stream converges on the
        measured best (falls back to the grid walk only by never having
        been constructed with a proposer)."""
        nxt = self._proposer(
            (self._best.partition_bytes, self._best.credit),
            self._best_time, dict(self.measured))
        if nxt is None:
            self.converged = True
            self._apply(self._best.partition_bytes, self._best.credit)
            log.info("tuner converged (proposer exhausted): "
                     "partition=%dKB credit=%d",
                     self._best.partition_bytes >> 10, self._best.credit)
            return
        pb, cr = nxt
        pi = min(range(len(PARTITION_GRID)),
                 key=lambda i: abs(PARTITION_GRID[i] - pb))
        ci = min(range(len(CREDIT_GRID)),
                 key=lambda i: abs(CREDIT_GRID[i] - cr))
        self._current = _Candidate(pi, ci)
        self._apply(self._current.partition_bytes, self._current.credit)

    def _rotate(self) -> None:
        if self._direction > 0:
            self._direction = -1
        else:
            self._direction = +1
            self._knob_i = (self._knob_i + 1) % len(self._knobs)

    def _neighbor(self) -> Optional[_Candidate]:
        c = self._current
        if self._knobs[self._knob_i] == "partition":
            i = c.part_idx + self._direction
            if 0 <= i < len(PARTITION_GRID):
                return _Candidate(i, c.credit_idx)
        else:
            i = c.credit_idx + self._direction
            if 0 <= i < len(CREDIT_GRID):
                return _Candidate(c.part_idx, i)
        return None

    # -- introspection ------------------------------------------------------
    @property
    def current(self) -> Tuple[int, int]:
        return (self._current.partition_bytes, self._current.credit)

    @property
    def best(self) -> Tuple[int, int]:
        return (self._best.partition_bytes, self._best.credit)
