"""Tensor declaration, key assignment, and partitioning.

TPU-native equivalent of the reference's tensor-declaration and partitioning
logic (``byteps/common/global.cc`` ``DeclareTensor`` and
``byteps/common/operations.cc`` ``InitTensor`` / key-list construction):

* Each named tensor is **declared** once; declaration order assigns a
  monotonically increasing tensor id, and **priority = -declaration order**
  — in backward passes, the last layers' gradients are declared first and so
  get the highest priority; they're produced first and consumed last, which
  is exactly what overlap wants.
* Each tensor is **partitioned** into chunks of at most
  ``BYTEPS_PARTITION_BYTES`` (default 4096000) so large tensors pipeline
  through the stages and interleave with smaller ones.
* Each partition gets a globally unique **key**; on the DCN tier, key → server
  assignment is ``key % num_server`` (the reference hashes partition keys to
  spread load across servers).

Partitioning here is in **elements** (derived from dtype itemsize) because the
TPU path slices jnp arrays rather than raw byte buffers.
"""

from __future__ import annotations

import dataclasses
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from byteps_tpu.common.config import get_config
from byteps_tpu.common.logging import bps_check, get_logger

log = get_logger("partition")

# Max partitions per declared tensor; keys are tensor_id * MAX_PARTS + i.
# 2**16 partitions * 4MB ≈ 256 GB per tensor — comfortably above any real
# tensor, and keeps keys stable as partition size is tuned downward.
MAX_PARTS_PER_TENSOR = 1 << 16


@dataclasses.dataclass(frozen=True)
class Partition:
    """One ~partition_bytes chunk of a declared tensor.

    Reference analog: one ``TensorTableEntry`` (byteps/common/common.h) —
    minus the runtime fields (buffers, callback), which live in the
    scheduler's task object here.
    """

    key: int           # globally unique partition key
    tensor_id: int
    part_idx: int      # index of this partition within its tensor
    offset: int        # element offset into the flattened tensor
    length: int        # element count
    priority: int      # = -tensor_id (higher = schedule earlier)
    # Sharded-wire hierarchical mode: the pod controller that carries this
    # partition over the DCN (rendezvous hash over the pod's controllers,
    # see OwnerTable). 0 — the only controller — everywhere else; the
    # field is assigned at hash time and is a LABEL (credit-pool identity,
    # trace attribution): live routing re-resolves through the OwnerTable
    # so an owner failover moves the wire without rewriting tasks.
    owner: int = 0


@dataclasses.dataclass
class TensorContext:
    """Per-declared-tensor state (reference analog: ``BPSContext``)."""

    name: str
    tensor_id: int
    shape: Tuple[int, ...]
    dtype: np.dtype
    partitions: List[Partition]

    @property
    def priority(self) -> int:
        return -self.tensor_id

    @property
    def num_elements(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


def partition_length(itemsize: int, partition_bytes: int) -> int:
    """Elements per partition for a given byte budget (≥1)."""
    return max(1, partition_bytes // max(1, itemsize))


def make_partitions(
    tensor_id: int,
    num_elements: int,
    itemsize: int,
    partition_bytes: Optional[int] = None,
) -> List[Partition]:
    if partition_bytes is None:
        partition_bytes = get_config().partition_bytes
    plen = partition_length(itemsize, partition_bytes)
    n_parts = max(1, -(-num_elements // plen))
    bps_check(
        n_parts <= MAX_PARTS_PER_TENSOR,
        f"tensor {tensor_id} needs {n_parts} partitions > {MAX_PARTS_PER_TENSOR}",
    )
    parts = []
    for i in range(n_parts):
        off = i * plen
        parts.append(
            Partition(
                key=tensor_id * MAX_PARTS_PER_TENSOR + i,
                tensor_id=tensor_id,
                part_idx=i,
                offset=off,
                length=min(plen, num_elements - off),
                priority=-tensor_id,
            )
        )
    return parts


class TensorRegistry:
    """Declaration table: name → TensorContext. Thread-safe.

    Reference analog: ``BytePSGlobal``'s declared-tensor table
    (``byteps/common/global.cc``).
    """

    def __init__(self, partition_bytes: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._by_name: Dict[str, TensorContext] = {}
        self._next_id = 0
        self._partition_bytes = partition_bytes

    @property
    def partition_bytes(self) -> int:
        if self._partition_bytes is not None:
            return self._partition_bytes
        return get_config().partition_bytes

    def declare(
        self,
        name: str,
        shape: Sequence[int],
        dtype,
    ) -> TensorContext:
        """Idempotent per name; first call fixes id/priority/partitioning."""
        dtype = np.dtype(dtype)
        with self._lock:
            ctx = self._by_name.get(name)
            if ctx is not None:
                bps_check(
                    tuple(shape) == ctx.shape and dtype == ctx.dtype,
                    f"tensor '{name}' re-declared with different shape/dtype "
                    f"({tuple(shape)}/{dtype} vs {ctx.shape}/{ctx.dtype})",
                )
                return ctx
            tid = self._next_id
            self._next_id += 1
            nelem = int(np.prod(shape)) if len(shape) else 1
            ctx = TensorContext(
                name=name,
                tensor_id=tid,
                shape=tuple(shape),
                dtype=dtype,
                partitions=make_partitions(
                    tid, nelem, dtype.itemsize, self.partition_bytes
                ),
            )
            self._by_name[name] = ctx
            log.debug(
                "declared tensor '%s' id=%d parts=%d priority=%d",
                name, tid, len(ctx.partitions), ctx.priority,
            )
            return ctx

    def get(self, name: str) -> Optional[TensorContext]:
        with self._lock:
            return self._by_name.get(name)

    def snapshot(self) -> List[Tuple[str, TensorContext]]:
        """Locked point-in-time view of every declared tensor — for
        cross-tensor walks (owner failover's moved-partition diff) that
        must not race declare()/repartition()."""
        with self._lock:
            return list(self._by_name.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_name)

    def repartition(self, partition_bytes: int) -> None:
        """Re-chunk all declared tensors (used by the auto-tuner)."""
        with self._lock:
            self._partition_bytes = partition_bytes
            for ctx in self._by_name.values():
                nelem = ctx.num_elements
                ctx.partitions = make_partitions(
                    ctx.tensor_id, nelem, ctx.dtype.itemsize, partition_bytes
                )


def owner_for_key(key: int, controllers, salt: int = 0) -> int:
    """Deterministic partition→controller placement: rendezvous hash over
    the given controller ranks (mirrors PSWorker._server_for_live's
    key→server hash, so owner remap composes with server failover — both
    layers move only the dead member's keys). zlib.crc32 is stable across
    processes/runs, unlike salted hash(); ``salt`` (BYTEPS_OWNER_SALT)
    lets a deployment reshuffle placement without renaming tensors."""
    ranks = list(controllers)
    bps_check(len(ranks) > 0, "owner_for_key: no live controllers")
    if len(ranks) == 1:
        return ranks[0]
    return max(ranks,
               key=lambda c: zlib.crc32(f"{key}:{c}:{salt}".encode()))


class OwnerTable:
    """Live-controller view for the sharded-wire hierarchical DCN tier.

    One per pod-controller process. Each partition key is owned by exactly
    one of the pod's ``n_controllers`` (rendezvous hash over the LIVE
    set): the owner alone COMPRESSes, PUSHes and PULLs that partition
    through its own NIC, dividing per-NIC DCN bytes by the live-controller
    count. ``fail(rank)`` shrinks the live set — only the dead
    controller's keys move (rendezvous property), exactly like PR3's
    server-side key remap. Thread-safe; ``owner()`` is resolved at stage
    execution time so a stage retry after a failover lands on the
    survivor.
    """

    def __init__(self, n_controllers: int, salt: int = 0) -> None:
        bps_check(n_controllers >= 1, "OwnerTable needs >= 1 controller")
        self._lock = threading.Lock()
        self._live = set(range(n_controllers))
        self.n_controllers = n_controllers
        self.salt = salt

    def live(self):
        with self._lock:
            return set(self._live)

    def owner(self, key: int) -> int:
        with self._lock:
            live = set(self._live)
        return owner_for_key(key, live, self.salt)

    def owner_in(self, key: int, live) -> int:
        """Placement under an explicit live set (failover diffing)."""
        return owner_for_key(key, live, self.salt)

    def fail(self, rank: int) -> bool:
        """Mark a controller dead; False if already dead. Refuses to kill
        the last controller (the pod would have no wire at all — that is
        the total-DCN-outage degraded path's job, not ours)."""
        with self._lock:
            if rank not in self._live or len(self._live) == 1:
                return False
            self._live.discard(rank)
            return True
