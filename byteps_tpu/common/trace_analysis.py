"""dPRO-style analysis of byteps_tpu chrome traces (SURVEY §5.1).

The joapolarbear fork exists largely to FEED its per-stage chrome traces to
dPRO (MLSys'22), which builds a global dataflow DAG from per-worker traces
and attributes step time to stages / finds the critical path. This module is
the TPU build's in-tree equivalent of that analysis pass: point it at a
trace from ``BYTEPS_TRACE_ON=1`` (or a ``merge_traces`` output combining
worker + server timelines) and it reports

* per-(rank, stage) service-time stats and busy fraction,
* per-partition lifecycles (REDUCE → … → COPYH2D chained by occurrence),
  splitting end-to-end latency into service time vs queue wait,
* per-step makespan with the partition that closed each step (the
  critical partition — dPRO's critical-path attribution at the
  granularity this scheduler controls),
* comm/comm overlap: how much PUSH/PULL wall time is hidden behind the
  ICI REDUCE stage (the pipelining the priority scheduler exists to buy).

CLI::

    python -m byteps_tpu.common.trace_analysis merged.json [--top 5] [--json]

Device-side compute lives in XLA and is profiled by ``jax.profiler``; this
pass covers the framework tier (scheduler, codec, DCN transport, server),
which is the tier the reference's timeline covers too.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Stage display order is DERIVED from the scheduler's stage-order
# registry (reference QueueType order, byteps/common/common.h), not
# hand-kept: importing stage_orders registers every pipeline's declared
# order (DCN/HYBRID/EAGER + the server's per-key rows — the light leaf
# module, so this CLI stays usable on an analysis-only box without
# jax), and any PipelineScheduler built in this process re-registers
# its live stage list. PR 4 had to remember to append ALLGATHER to the
# old hand-kept list by hand; now a stage exists in the order the
# moment its pipeline declares it (the pipelines bps_check their built
# stage lists against the declared constants). Unknown stages still
# sort after, alphabetically.
from byteps_tpu.common import stage_orders as _orders  # noqa: F401
from byteps_tpu.common.scheduler import registered_stage_order
from byteps_tpu.common.stage_orders import SERVER_STAGE_ORDER as _SERVER_ROWS


def stage_order() -> List[str]:
    """Current pipeline-ordered stage names (see module comment)."""
    return registered_stage_order()


def load_events(path: str) -> List[Dict[str, Any]]:
    """Read a chrome trace file; accepts {traceEvents: [...]}, a bare
    event list, or a flight-recorder post-mortem dump (degraded input:
    the per-step ring's stage percentiles are synthesized into one span
    per stage per step, so the same analysis/extraction passes run —
    minus per-partition detail)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        if "traceEvents" in doc:
            events = doc["traceEvents"]
        elif "steps" in doc and "fault_events" in doc:
            events = flight_dump_events(doc)
        else:
            raise ValueError(
                f"{path}: neither a chrome trace (no 'traceEvents') nor "
                f"a flight-recorder dump (no 'steps'/'fault_events'); "
                f"keys: {sorted(doc)[:8]}"
            )
    else:
        events = doc
    return [e for e in events if isinstance(e, dict)]


def flight_dump_events(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Synthesize chrome-style complete events from a flight-recorder
    post-mortem's per-step ring: one span per (step, stage) with the
    stage's cumulative run p50 as the duration, laid out on the ring's
    own t_s timeline. Coarse by construction — it answers "which stage
    moved" and feeds the degraded simulator extraction, not per-partition
    attribution."""
    events: List[Dict[str, Any]] = []
    for s in doc.get("steps", []):
        ts = float(s.get("t_s", 0.0)) * 1e6
        for stage, row in (s.get("stages") or {}).items():
            dur = row.get("run_p50_us")
            if not dur:
                continue
            events.append({
                "name": f"step{s.get('step')}",
                "cat": "byteps", "ph": "X",
                "ts": ts, "dur": float(dur),
                "pid": 0, "tid": str(stage),
                "args": {},
            })
    return events


def _complete_events(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [
        e for e in events
        if e.get("ph") == "X" and "ts" in e and "dur" in e
    ]


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


def _union_intervals(
    iv: List[Tuple[float, float]],
) -> List[Tuple[float, float]]:
    """Merge overlapping [start, end) intervals; returns sorted disjoint set."""
    if not iv:
        return []
    iv = sorted(iv)
    out = [iv[0]]
    for s, e in iv[1:]:
        ls, le = out[-1]
        if s <= le:
            out[-1] = (ls, max(le, e))
        else:
            out.append((s, e))
    return out


def _overlap_len(a: List[Tuple[float, float]], b: List[Tuple[float, float]]) -> float:
    """Total overlap between two DISJOINT-sorted interval sets."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def stage_stats(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-(pid, stage) service-time stats over complete events.

    ``busy_frac`` is the union of the stage's busy intervals over the whole
    trace span — >0.5 on PUSH means the wire is the bottleneck; low busy
    with high total means bursty (queue-limited) traffic.
    """
    xs = _complete_events(events)
    if not xs:
        return []
    t0 = min(e["ts"] for e in xs)
    t1 = max(e["ts"] + e["dur"] for e in xs)
    span = max(t1 - t0, 1e-9)
    groups: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
    for e in xs:
        groups.setdefault((e.get("pid"), e.get("tid")), []).append(e)

    order = stage_order()

    def stage_key(item):
        (pid, tid), _ = item
        try:
            si = order.index(tid)
        except ValueError:
            si = len(order)
        # numeric ranks first in numeric order, then string pids (servers)
        pid_key = (0, pid, "") if isinstance(pid, int) else (1, 0, str(pid))
        return (pid_key, si, str(tid))

    rows = []
    for (pid, tid), evs in sorted(groups.items(), key=stage_key):
        durs = sorted(e["dur"] for e in evs)
        busy = _union_intervals([(e["ts"], e["ts"] + e["dur"]) for e in evs])
        busy_us = sum(e - s for s, e in busy)
        rows.append({
            "pid": pid,
            "stage": tid,
            "count": len(durs),
            "total_us": sum(durs),
            "mean_us": sum(durs) / len(durs),
            "p50_us": _percentile(durs, 0.5),
            "p95_us": _percentile(durs, 0.95),
            "max_us": durs[-1],
            "busy_frac": busy_us / span,
        })
    return rows


def partition_lifecycles(
    events: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Chain each partition's per-stage events into lifecycles.

    Events for one partition share (pid, name); the i-th occurrence of a
    partition in EACH stage belongs to round i (stages run in pipeline
    order, so per-stage occurrence index is the round number — the same
    reconstruction dPRO does from the reference's traces). A lifecycle's
    ``latency`` is last-stage end − first-stage start; ``service`` the sum
    of stage durations; ``queue_wait`` the difference (time spent parked in
    the priority queues / awaiting the server round).
    """
    per_stage_seen: Dict[Tuple[Any, str, Any], int] = {}
    rounds: Dict[Tuple[Any, str, int], List[Dict[str, Any]]] = {}
    for e in sorted(_complete_events(events), key=lambda e: e["ts"]):
        pid, name, tid = e.get("pid"), str(e.get("name")), e.get("tid")
        if tid in _SERVER_ROWS:
            continue  # server rows: per-key, not per-partition-occurrence
        occ = per_stage_seen.get((pid, name, tid), 0)
        per_stage_seen[(pid, name, tid)] = occ + 1
        rounds.setdefault((pid, name, occ), []).append(e)

    out = []
    for (pid, name, occ), evs in rounds.items():
        evs.sort(key=lambda e: e["ts"])
        start = evs[0]["ts"]
        end = max(e["ts"] + e["dur"] for e in evs)
        service = sum(e["dur"] for e in evs)
        args = evs[0].get("args", {})
        out.append({
            "pid": pid,
            "name": name,
            "round": occ,
            "stages": [e["tid"] for e in evs],
            "start_us": start,
            "end_us": end,
            "latency_us": end - start,
            "service_us": service,
            "queue_wait_us": max(0.0, (end - start) - service),
            "key": args.get("key"),
            "priority": args.get("priority"),
            "length": args.get("length"),
        })
    out.sort(key=lambda r: (r["round"], r["start_us"]))
    return out


def step_makespans(
    lifecycles: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Per-round makespan + the critical (last-finishing) partition."""
    by_round: Dict[int, List[Dict[str, Any]]] = {}
    for lc in lifecycles:
        by_round.setdefault(lc["round"], []).append(lc)
    rows = []
    for rnd in sorted(by_round):
        lcs = by_round[rnd]
        start = min(l["start_us"] for l in lcs)
        end = max(l["end_us"] for l in lcs)
        crit = max(lcs, key=lambda l: l["end_us"])
        rows.append({
            "round": rnd,
            "partitions": len(lcs),
            "makespan_us": end - start,
            "critical_partition": crit["name"],
            "critical_pid": crit["pid"],
            "critical_latency_us": crit["latency_us"],
            "critical_queue_wait_us": crit["queue_wait_us"],
        })
    return rows


def comm_overlap(events: Sequence[Dict[str, Any]]) -> Dict[str, float]:
    """How much DCN wire time (PUSH+PULL) is hidden behind ICI REDUCE.

    The priority scheduler's whole job is to overlap partition N's PUSH
    with partition N+1's REDUCE (SURVEY §3.2 — "the single most important
    behavior to preserve"). ``hidden_frac`` == 0 means fully serialized;
    → 1 means the wire rides entirely under compute-side reduction.
    Overlap is per-rank (one rank's REDUCE cannot hide another rank's
    wire time) and summed, so merged multi-rank traces read correctly.
    """
    reduce_iv: Dict[Any, List[Tuple[float, float]]] = {}
    wire_iv: Dict[Any, List[Tuple[float, float]]] = {}
    for e in _complete_events(events):
        tid = e.get("tid")
        iv = (e["ts"], e["ts"] + e["dur"])
        if tid == "REDUCE":
            reduce_iv.setdefault(e.get("pid"), []).append(iv)
        elif tid in ("PUSH", "PULL"):
            wire_iv.setdefault(e.get("pid"), []).append(iv)
    reduce_us = wire_us = hidden = 0.0
    for pid, ivs in wire_iv.items():
        w = _union_intervals(ivs)
        wire_us += sum(e - s for s, e in w)
        hidden += _overlap_len(_union_intervals(reduce_iv.get(pid, [])), w)
    for ivs in reduce_iv.values():
        reduce_us += sum(e - s for s, e in _union_intervals(ivs))
    return {
        "reduce_busy_us": reduce_us,
        "wire_busy_us": wire_us,
        "hidden_us": hidden,
        "hidden_frac": hidden / wire_us if wire_us else 0.0,
    }


def analyze(events: Sequence[Dict[str, Any]], top: int = 5) -> Dict[str, Any]:
    """Full report over one trace's events."""
    lifecycles = partition_lifecycles(events)
    slowest = sorted(lifecycles, key=lambda l: -l["latency_us"])[:top]
    xs = _complete_events(events)
    span = (
        max(e["ts"] + e["dur"] for e in xs) - min(e["ts"] for e in xs)
        if xs else 0.0
    )
    return {
        "span_us": span,
        "events": len(xs),
        "stages": stage_stats(events),
        "steps": step_makespans(lifecycles),
        "slowest_partitions": slowest,
        "comm_overlap": comm_overlap(events),
    }


def _fmt_us(v: float) -> str:
    return f"{v / 1e3:.3f}ms" if v >= 1e3 else f"{v:.1f}us"


def render(report: Dict[str, Any]) -> str:
    """Human-readable text report."""
    out = []
    out.append(
        f"trace: {report['events']} complete events over "
        f"{_fmt_us(report['span_us'])}"
    )
    out.append("")
    out.append(f"{'pid':>6} {'stage':<14} {'n':>5} {'total':>10} "
               f"{'mean':>9} {'p50':>9} {'p95':>9} {'max':>9} {'busy':>6}")
    for r in report["stages"]:
        out.append(
            f"{str(r['pid']):>6} {str(r['stage']):<14} {r['count']:>5} "
            f"{_fmt_us(r['total_us']):>10} {_fmt_us(r['mean_us']):>9} "
            f"{_fmt_us(r['p50_us']):>9} {_fmt_us(r['p95_us']):>9} "
            f"{_fmt_us(r['max_us']):>9} {r['busy_frac'] * 100:>5.1f}%"
        )
    steps = report["steps"]
    if steps:
        out.append("")
        out.append("per-round makespan (critical partition = last to finish):")
        for s in steps:
            out.append(
                f"  round {s['round']:>3}: {_fmt_us(s['makespan_us']):>10} "
                f"over {s['partitions']} partitions; critical "
                f"{s['critical_partition']} (pid {s['critical_pid']}, "
                f"latency {_fmt_us(s['critical_latency_us'])}, "
                f"queued {_fmt_us(s['critical_queue_wait_us'])})"
            )
    if report["slowest_partitions"]:
        out.append("")
        out.append("slowest partition lifecycles:")
        for l in report["slowest_partitions"]:
            out.append(
                f"  {l['name']} r{l['round']} pid {l['pid']}: "
                f"latency {_fmt_us(l['latency_us'])} = service "
                f"{_fmt_us(l['service_us'])} + queue "
                f"{_fmt_us(l['queue_wait_us'])} "
                f"[{' > '.join(map(str, l['stages']))}]"
            )
    ov = report["comm_overlap"]
    if ov["wire_busy_us"]:
        out.append("")
        out.append(
            f"comm overlap: {_fmt_us(ov['hidden_us'])} of "
            f"{_fmt_us(ov['wire_busy_us'])} PUSH/PULL wall time hidden "
            f"behind REDUCE ({ov['hidden_frac'] * 100:.1f}%)"
        )
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m byteps_tpu.common.trace_analysis",
        description="dPRO-style per-stage analysis of a byteps_tpu "
                    "chrome trace (see docs/timeline.md)",
    )
    ap.add_argument("trace", help="trace json (per-rank dump or merged)")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest partitions to list (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON instead of text")
    ap.add_argument("--whatif-export", metavar="OUT.json", default=None,
                    help="lift this recorded run into the what-if "
                    "simulator's calibrated cost model (byteps_tpu/sim, "
                    "docs/whatif.md) and write it as JSON: per-stage "
                    "service fits from the same lifecycle/stat passes "
                    "this CLI reports, codec table, round slack. The "
                    "run's resolved config comes from the trace "
                    "metadata's 'config' stamp; flight-recorder dumps "
                    "are accepted as degraded input.")
    ns = ap.parse_args(argv)
    if ns.whatif_export:
        return _whatif_export(ns.trace, ns.whatif_export)
    report = analyze(load_events(ns.trace), top=ns.top)
    if ns.json:
        json.dump(report, sys.stdout, indent=1)
        print()
    else:
        print(render(report))
    return 0


def _whatif_export(trace_path: str, out_path: str) -> int:
    """One command: recorded run -> simulator calibration input.
    Imported lazily — the plain analysis CLI stays usable on a box
    without the data plane's dependencies."""
    from byteps_tpu.sim.extract import (
        cost_model_from_events,
        cost_model_from_flight_dump,
    )

    with open(trace_path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "steps" in doc and "traceEvents" not in doc:
        model = cost_model_from_flight_dump(doc)
        src = "flight-recorder dump (degraded)"
    else:
        events = (doc.get("traceEvents", doc)
                  if isinstance(doc, dict) else doc)
        events = [e for e in events if isinstance(e, dict)]
        config = (doc.get("metadata", {}).get("config", {})
                  if isinstance(doc, dict) else {})
        # the trace metadata's Config.snapshot() names the wire knobs;
        # the codec is not a Config field — callers record it in
        # metadata or rely on the recorded-codec default (raw)
        model = cost_model_from_events(events, config=config)
        src = "chrome trace"
    with open(out_path, "w") as f:
        json.dump(model.to_dict(), f, indent=1)
    print(f"wrote calibrated cost model from {src} to {out_path} "
          f"({len(model.tensors)} tensor(s), "
          f"{len(model.stage_fits)} stage fits, "
          f"round slack {model.round_slack_us:.0f}us)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
