"""Real-socket NIC: framed, CRC-checked TCP transport between processes.

Everything the chaos tier proved so far (PRs 3/5/10) ran over emulated
in-process NICs — the gradient tier's loss/corruption/death weather is
injected at the PSWorker wire boundary, and the serve tier's KV-block
migration delivers by direct method call (``target.ingest_block``).
This module is the REAL link those seams plug into when the two ends
live in different OS processes:

* **Frame** — ``[u32 magic 'BNC1'][u32 channel][u32 seq][u32 flags]
  [u32 body_len][u32 crc32]`` + body. The CRC is computed over the body
  and verified on BOTH directions, so on-wire damage is detected, never
  delivered — the same contract as the gradient frame's CRC32 (PR 3)
  and the KV frame's (:mod:`byteps_tpu.serve.kv_wire`), now catching
  REAL corruption on a real socket instead of an injected byte flip.
* **Listener** (:class:`SocketNicListener`) — one accept thread, one
  reader thread per connection, per-channel handlers registered by the
  consumer (the KV endpoint registers :data:`CH_KV_BLOCK`). The listen
  path binds through :func:`byteps_tpu.server.any_port`, the SAME
  ephemeral-port-squatter sidestep the native summation server uses
  (this image's ip_local_port_range starts at 16000, so any client
  socket can squat a fixed port) — the workaround is derived once,
  reused here, never a third time.
* **Client** (:class:`SocketNicClient`) — one lazily-connected socket
  per calling thread (the PSWorker connection-pool discipline), a
  blocking ``request`` per frame. Real connection errors surface in
  the EXISTING retryable/wire-death taxonomy: ``ECONNRESET``/refused
  arrive as ``ConnectionError`` subclasses and a recv deadline raises
  ``TimeoutError`` — exactly the types
  ``server._is_retryable_wire_error`` classifies retryable — while a
  CRC reject comes back as :class:`SockWireCorruption`
  (``retryable=True``: the re-send is pristine) and a handler-side
  failure as :class:`SockRemoteError` (``retryable=False`` unless the
  relayed type says otherwise). Payload bytes are shaped through an
  optional :class:`~byteps_tpu.server.pacer.DcnPacer`
  (``BYTEPS_SOCKET_MBPS``): the PR 1 token bucket, now a shaper on a
  real link rather than an emulated one.

An optional :class:`~byteps_tpu.common.faults.FaultPlan` intercepts
each client request (op ``"push"``): ``corrupt`` flips a byte of the
ENCODED frame after the CRC was stamped — so the damage crosses the
real wire and the LISTENER's CRC catches it — ``kill``/``down`` drop
the socket before sending, ``timeout`` sends then reports the reply
lost. Same grammar, same seeded determinism, real bytes.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
import zlib
from typing import Callable, Dict, Optional

from byteps_tpu.common.faults import (
    FaultPlan,
    InjectedConnectionError,
    InjectedTimeout,
)
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry

log = get_logger("socknic")

__all__ = [
    "CH_PING", "CH_KV_BLOCK", "SockWireCorruption", "SockRemoteError",
    "SocketNicListener", "SocketNicClient",
]

_MAGIC = 0x42_4E_43_31  # "BNC1"
_HDR = struct.Struct("<IIIIII")  # magic, channel, seq, flags, len, crc
_FLAG_REPLY = 0x1
_FLAG_ERROR = 0x2

# channel ids are a tiny fixed registry, not a negotiation: both ends
# of a wire are this codebase
CH_PING = 0
CH_KV_BLOCK = 1

# per-instance registry series (the PR 6 pacer.p<N> rule): listeners
# and clients each get their own socknic.l<N>./socknic.c<N>. counters
_LISTENER_SEQ = itertools.count()
_CLIENT_SEQ = itertools.count()


class SockWireCorruption(RuntimeError):
    """Frame CRC mismatch — the bytes were damaged on the wire (or by an
    armed ``corrupt`` fault rule). Retryable: the re-send re-encodes
    from the pristine payload."""

    retryable = True


class SockRemoteError(RuntimeError):
    """A handler on the listener side raised; the error crossed back as
    a typed reply. Not retryable by default — re-sending the same bytes
    re-raises the same handler error — unless the relayed type is
    mapped to something that says otherwise (``error_types``)."""

    retryable = False


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionResetError(
                "socket closed mid-frame (peer died or reset)")
        buf.extend(chunk)
    return bytes(buf)


def _frame(channel: int, seq: int, flags: int, body: bytes) -> bytes:
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return _HDR.pack(_MAGIC, channel, seq, flags, len(body), crc) + body


def _read_frame(conn: socket.socket):
    """-> (channel, seq, flags, body, crc_ok). A bad CRC is reported,
    not raised: the READER survives a damaged frame (the peer retries),
    only a malformed header kills the connection."""
    hdr = _recv_exact(conn, _HDR.size)
    magic, channel, seq, flags, blen, crc = _HDR.unpack(hdr)
    if magic != _MAGIC:
        raise ConnectionResetError(
            f"bad socknic frame magic {magic:#x} — desynced stream")
    body = _recv_exact(conn, blen) if blen else b""
    return channel, seq, flags, body, (zlib.crc32(body) & 0xFFFFFFFF) == crc


class SocketNicListener:
    """One process's inbound NIC: accept loop + per-channel handlers.

    ``handlers[channel] = fn(body: bytes) -> bytes`` runs on the
    connection's reader thread; its return value is the reply body. A
    handler exception is relayed to the client as a typed error reply
    (``"ExcTypeName: message"``) — the client re-raises it through its
    ``error_types`` map. A frame whose CRC fails is rejected with
    :class:`SockWireCorruption` (counted in ``socknic.l<N>.crc_rejects``)
    and the connection stays up: corruption costs a retry, never a link.
    """

    def __init__(self, port: int, attempts: int = 16, stride: int = 1,
                 host: str = "127.0.0.1"):
        # the native server's ephemeral-port-squatter sidestep, reused
        # (satellite: never re-derive the ip_local_port_range=16000
        # workaround); imported lazily to keep common -> server one-way
        # at import time
        from byteps_tpu.server import any_port

        self._handlers: Dict[int, Callable[[bytes], Optional[bytes]]] = {
            CH_PING: lambda body: body,  # echo — liveness probe
        }
        self._conns: list = []
        self._lock = threading.Lock()
        self._closed = False
        tag = f"socknic.l{next(_LISTENER_SEQ)}"
        _reg = get_registry()
        self._m_accepts = _reg.counter(f"{tag}.accepts")
        self._m_frames = _reg.counter(f"{tag}.frames")
        self._m_crc_rejects = _reg.counter(f"{tag}.crc_rejects")
        self._m_handler_errors = _reg.counter(f"{tag}.handler_errors")
        self._m_bytes_in = _reg.counter(f"{tag}.bytes_in")
        self._m_bytes_out = _reg.counter(f"{tag}.bytes_out")

        def _bind(p: int) -> socket.socket:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.bind((host, p))
            except OSError:
                s.close()
                raise
            return s

        self._sock = any_port(_bind, port, attempts=attempts,
                              stride=stride)
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{tag}.accept", daemon=True)
        self._accept_thread.start()

    def register(self, channel: int,
                 fn: Callable[[bytes], Optional[bytes]]) -> None:
        self._handlers[int(channel)] = fn

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            self._m_accepts.inc()
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed:
                channel, seq, _flags, body, crc_ok = _read_frame(conn)
                self._m_frames.inc()
                self._m_bytes_in.inc(_HDR.size + len(body))
                if not crc_ok:
                    # damaged on the wire: reject loudly, keep the link —
                    # the client's retry re-sends pristine bytes
                    self._m_crc_rejects.inc()
                    reply = _frame(
                        channel, seq, _FLAG_REPLY | _FLAG_ERROR,
                        b"SockWireCorruption: frame CRC mismatch at "
                        b"the listener")
                else:
                    fn = self._handlers.get(channel)
                    try:
                        if fn is None:
                            raise SockRemoteError(
                                f"no handler for channel {channel}")
                        out = fn(body) or b""
                        reply = _frame(channel, seq, _FLAG_REPLY, out)
                    except Exception as e:  # noqa: BLE001 - relayed to
                        # the client as a TYPED reply; the wire itself
                        # must survive any handler failure
                        self._m_handler_errors.inc()
                        msg = f"{type(e).__name__}: {e}".encode(
                            "utf-8", "replace")
                        reply = _frame(channel, seq,
                                       _FLAG_REPLY | _FLAG_ERROR, msg)
                conn.sendall(reply)
                self._m_bytes_out.inc(len(reply))
        except (ConnectionError, OSError):
            pass  # peer went away — its client surface reports it
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class SocketNicClient:
    """One process's outbound NIC to a listener: blocking request/reply.

    Thread-safe the PSWorker way — one lazily-dialed socket per calling
    thread — so concurrent stage-pool threads never interleave frames.
    Errors keep their taxonomy: connect/sendall/recv surface
    ``ConnectionError`` (dead peer — retryable, and the next attempt
    redials), the recv deadline raises ``TimeoutError`` (retryable; the
    socket is dropped so no stale reply can be misread), a CRC reject
    raises :class:`SockWireCorruption`, and a relayed handler error is
    re-raised through ``error_types`` (falling back to
    :class:`SockRemoteError`, not retryable).
    """

    def __init__(self, host: str, port: int,
                 timeout_ms: Optional[int] = None,
                 pacer=None,
                 fault_plan: Optional[FaultPlan] = None,
                 error_types: Optional[Dict[str, type]] = None):
        from byteps_tpu.common.config import get_config

        cfg = get_config()
        self.host = host
        self.port = int(port)
        self._timeout_s = (
            timeout_ms if timeout_ms is not None
            else getattr(cfg, "socket_timeout_ms", 10000)) / 1e3
        self._pacer = pacer
        self._plan = fault_plan
        self._types = {"SockWireCorruption": SockWireCorruption}
        self._types.update(error_types or {})
        self._tls = threading.local()
        self._seq = itertools.count(1)
        self._closed = False
        self._all_socks: list = []
        self._socks_lock = threading.Lock()
        tag = f"socknic.c{next(_CLIENT_SEQ)}"
        _reg = get_registry()
        self._m_requests = _reg.counter(f"{tag}.requests")
        self._m_bytes_sent = _reg.counter(f"{tag}.bytes_sent")
        self._m_bytes_recv = _reg.counter(f"{tag}.bytes_recv")
        self._m_conn_errors = _reg.counter(f"{tag}.conn_errors")
        self._m_timeouts = _reg.counter(f"{tag}.timeouts")
        self._m_crc_errors = _reg.counter(f"{tag}.crc_errors")
        self._m_remote_errors = _reg.counter(f"{tag}.remote_errors")

    # -- connection management (per-thread, PSWorker-style) ------------------
    def _sock_get(self) -> socket.socket:
        s = getattr(self._tls, "sock", None)
        if s is None:
            if self._closed:
                raise RuntimeError("SocketNicClient is closed")
            s = socket.create_connection((self.host, self.port),
                                         timeout=self._timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._tls.sock = s
            with self._socks_lock:
                self._all_socks.append(s)
        return s

    def _sock_drop(self) -> None:
        s = getattr(self._tls, "sock", None)
        if s is not None:
            self._tls.sock = None
            try:
                s.close()
            except OSError:
                pass

    def request(self, channel: int, body: bytes) -> bytes:
        """One framed request; returns the reply body. Exceptions leave
        the socket DROPPED so the caller's retry redials clean."""
        self._m_requests.inc()
        seq = next(self._seq)
        buf = bytearray(_frame(channel, seq, 0, body))
        inj = self._plan.intercept("push", -1) if self._plan else None
        if inj is not None and inj.kind in ("kill", "down"):
            self._sock_drop()
            raise InjectedConnectionError(
                f"injected {inj.kind} on socknic request ch={channel}")
        if inj is not None and inj.kind == "corrupt":
            # flip a BODY byte after the CRC was stamped: the damage
            # rides the real wire and the LISTENER's CRC catches it
            i = _HDR.size + (inj.corrupt_at % max(1, len(body)))
            buf[i] ^= 0xFF
        if self._pacer is not None:
            self._pacer.throttle_send(len(buf))
        try:
            s = self._sock_get()
            s.sendall(bytes(buf))
            self._m_bytes_sent.inc(len(buf))
            rch, rseq, rflags, rbody, crc_ok = _read_frame(s)
        except socket.timeout:
            self._m_timeouts.inc()
            self._sock_drop()
            raise TimeoutError(
                f"socknic recv deadline ({self._timeout_s:.1f}s) to "
                f"{self.host}:{self.port}") from None
        except ConnectionError:
            self._m_conn_errors.inc()
            self._sock_drop()
            raise
        except OSError as e:
            # e.g. EPIPE on a half-dead socket: same class of death
            self._m_conn_errors.inc()
            self._sock_drop()
            raise ConnectionError(
                f"socknic request to {self.host}:{self.port} failed: "
                f"{e}") from e
        self._m_bytes_recv.inc(_HDR.size + len(rbody))
        if self._pacer is not None:
            self._pacer.throttle_recv(_HDR.size + len(rbody))
        if rseq != seq or rch != channel:
            self._sock_drop()
            raise ConnectionError(
                f"socknic reply desync (sent ch={channel} seq={seq}, "
                f"got ch={rch} seq={rseq})")
        if not crc_ok:
            self._m_crc_errors.inc()
            raise SockWireCorruption(
                "socknic reply CRC mismatch — frame damaged in flight")
        if rflags & _FLAG_ERROR:
            name, _, msg = rbody.decode("utf-8", "replace").partition(": ")
            exc = self._types.get(name)
            if name == "SockWireCorruption":
                self._m_crc_errors.inc()
            else:
                self._m_remote_errors.inc()
            if exc is not None:
                raise exc(msg)
            raise SockRemoteError(f"{name}: {msg}")
        if inj is not None and inj.kind == "timeout":
            # delivered, reply lost: the retry's re-send is the peer
            # handler's idempotency problem, same as every wire seam
            self._sock_drop()
            raise InjectedTimeout(
                f"injected timeout on socknic request ch={channel}")
        return rbody

    def ping(self, payload: bytes = b"socknic") -> bytes:
        return self.request(CH_PING, payload)

    def close(self) -> None:
        self._closed = True
        with self._socks_lock:
            socks, self._all_socks = self._all_socks, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
