"""Credit-based priority scheduler — the ByteScheduler core.

TPU-native equivalent of ``byteps/common/scheduled_queue.cc`` +
``byteps/common/core_loops.cc``. The reference runs ~12 background threads,
one per pipeline stage (COORDINATE_REDUCE → REDUCE → COPYD2H → ... → PUSH →
PULL → ... → BROADCAST), each popping the highest-priority ready partition
from a per-stage ``BytePSScheduledQueue``; the PUSH stage additionally
enforces a **credit** budget (at most ``BYTEPS_SCHEDULING_CREDIT`` partitions
in flight).

On TPU the picture simplifies: XLA owns device-side ordering within a stream,
and JAX dispatch is already async. What must be preserved is the *semantics*
that made BytePS fast (SURVEY §3.2 — "the single most important behavior to
preserve"):

* partitions are issued **in priority order** (priority = -declaration
  order, ties broken by key), regardless of arrival order;
* at most ``credit`` partitions are in flight at once, so a late-arriving
  high-priority partition can still jump ahead of queued low-priority ones
  instead of sitting behind a fully-committed queue;
* completion frees a credit and immediately pumps the queue.

The scheduler is stage-generic: a ``Pipeline`` is a list of named stages,
each with a dispatch function (sync or async). Per-partition per-stage
chrome-trace events are emitted (SURVEY §5.1), giving dPRO-style timelines.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence

from byteps_tpu.common.flight_recorder import get_flight_recorder
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.common.partition import Partition
from byteps_tpu.common.tracing import TraceRecorder

log = get_logger("scheduler")


# --- stage-order registry ----------------------------------------------------
# Pipeline-order of every stage name any scheduler has declared, merged
# across pipelines (order-preserving: a new name is inserted after its
# predecessor in the registering sequence). This is what
# ``trace_analysis`` sorts its display by — derived from the pipelines
# that EMIT the events instead of a hand-kept list that had to remember
# ALLGATHER by hand (PR 4). Pipelines register at import time (the
# offline-analysis case: dcn_adapter declares the worker orders;
# trace_analysis adds the server rows after them) AND every PipelineScheduler
# re-registers its actual stage list at construction, so a stage added
# to a constructor without updating the declared constant still lands
# in the order — and the coverage test catches the drift.
_stage_order: List[str] = []
_stage_order_lock = threading.Lock()

# sequential id per PipelineScheduler: the credit-occupancy gauge is a
# per-scheduler series — two concurrent schedulers (bench's two-worker
# legs run two DcnCores in one process) sharing one gauge would mask
# each other last-writer-wins, exactly when occupancy matters
_SCHED_SEQ = itertools.count()


def register_stage_order(names: Sequence[str]) -> None:
    """Merge a pipeline's stage-name sequence into the global order:
    each new name lands after its last already-known predecessor in the
    registering sequence, or before its first known successor, or at the
    end (a pipeline unrelated to every existing one appends whole)."""
    seq = [str(n) for n in names]
    with _stage_order_lock:
        for i, n in enumerate(seq):
            if n in _stage_order:
                continue
            pred = -1
            for p in seq[:i]:
                if p in _stage_order:
                    pred = max(pred, _stage_order.index(p))
            if pred >= 0:
                _stage_order.insert(pred + 1, n)
                continue
            succ = None
            for q in seq[i + 1:]:
                if q in _stage_order:
                    succ = _stage_order.index(q)
                    break
            if succ is not None:
                _stage_order.insert(succ, n)
            else:
                _stage_order.append(n)


def registered_stage_order() -> List[str]:
    with _stage_order_lock:
        return list(_stage_order)


class StallError(TimeoutError):
    """A Handle.wait() that did not complete in time — including a wait
    capped by ``BYTEPS_HANDLE_DEADLINE_MS``, which converts a would-be
    infinite wait (a dead peer worker with no lease armed, a wedged
    server) into THIS diagnosable error instead of a silent hang.

    Carries what a stall report needs: which partitions completed, and —
    when the owning pipeline attached a ``handle.diag`` callback — the
    per-stage/per-server robustness counters at the moment of the stall
    (retries, timeouts, failovers, live servers, health-probe ages, credit
    pools), so the report shows WHY fail-over/retry did or did not fire.
    """

    def __init__(self, handle_name: str, waited_s: Optional[float],
                 done_parts: List[int], total_parts: int,
                 diag: Optional[Dict[str, Any]] = None,
                 deadline_capped: bool = False):
        cap = (" (BYTEPS_HANDLE_DEADLINE_MS cap)" if deadline_capped
               else "")
        waited = "?" if waited_s is None else f"{waited_s:.1f}"
        super().__init__(
            f"handle '{handle_name}' stalled: {len(done_parts)}/"
            f"{total_parts} partition(s) done after {waited}s{cap}; "
            f"diagnostics: {diag if diag is not None else 'none attached'}")
        self.handle_name = handle_name
        self.done_parts = done_parts
        self.total_parts = total_parts
        self.diag = diag
        self.deadline_capped = deadline_capped
        # flight-recorder post-mortem (per-step metric ring + recent
        # FAULT events), attached at raise time by Handle.wait()
        self.post_mortem: Optional[Dict[str, Any]] = None


class PartitionFailure(RuntimeError):
    """A handle failed because one partition's pipeline failed.

    Names the failed partition and attaches the per-partition results that
    HAD completed when the failure froze the handle (``partial_results`` —
    a snapshot: later sibling completions do not mutate a failed handle).
    The original stage exception is ``__cause__``/``cause``.
    """

    def __init__(self, handle_name: str, part_idx: Optional[int],
                 cause: BaseException, partial_results: Dict[int, Any]):
        part = "?" if part_idx is None else str(part_idx)
        super().__init__(
            f"handle '{handle_name}' failed at partition {part}: "
            f"{type(cause).__name__}: {cause} "
            f"({len(partial_results)} sibling partition(s) completed)")
        self.handle_name = handle_name
        self.part_idx = part_idx
        self.cause = cause
        self.partial_results = partial_results
        self.__cause__ = cause
        # flight-recorder post-mortem, attached by Handle._partition_failed
        self.post_mortem: Optional[Dict[str, Any]] = None


class Handle:
    """Completion handle for one enqueued tensor (all its partitions).

    Reference analog: the int handle from ``HandleManager``
    (byteps/torch/handle_manager.cc); ``wait()`` is ``wait_and_clear``.

    Failure freezes the handle: the first ``_partition_failed`` snapshots
    the results collected so far into a :class:`PartitionFailure`, and
    every later sibling completion is dropped — ``wait()`` after failure
    must hand back a stable error, not a dict that sibling stage threads
    are still mutating underneath the caller.
    """

    def __init__(self, name: str, num_partitions: int) -> None:
        self.name = name
        self._num_partitions = num_partitions
        self._remaining = num_partitions
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._stall_recorded = False  # one FAULT-ring event per handle
        self.results: Dict[int, Any] = {}  # part_idx -> stage-pipeline output
        # Optional stall-diagnostics callback attached by the owning
        # pipeline: () -> dict of per-stage/per-server counters, folded
        # into the StallError a timed-out wait() raises.
        self.diag: Optional[Callable[[], Dict[str, Any]]] = None

    def _partition_done(self, part_idx: int, result: Any) -> None:
        with self._lock:
            if self._error is not None:
                return  # failed handle is frozen
            self.results[part_idx] = result
            self._remaining -= 1
            if self._remaining <= 0:
                self._event.set()

    def _partition_failed(self, exc: BaseException,
                          part_idx: Optional[int] = None) -> None:
        with self._lock:
            first = self._error is None
            if first:
                err = PartitionFailure(
                    self.name, part_idx, exc, dict(self.results))
                self._error = err
            else:
                # already failed and signalled; nothing left to do
                return
        # flight-recorder post-mortem rides the FIRST failure
        # (docs/observability.md): the ring shows the steps leading up
        # to it, not just the moment of death. Assembled OUTSIDE the
        # handle lock (the registry snapshot must not block sibling
        # completions or waiters), the event signalled right after the
        # attach so a woken waiter always sees it, and the optional
        # FILE dump deferred past the signal — a slow disk must not
        # hold every waiter long enough to misread the failure as a
        # stall.
        fr = pm = None
        try:
            fr = get_flight_recorder()
            fr.record_event("partition_failure", {
                "handle": self.name, "part": part_idx,
                "error": type(exc).__name__})
            pm = fr.post_mortem(reason="partition_failure", dump=False)
            err.post_mortem = pm
        except Exception:  # noqa: BLE001 - telemetry must never mask
            pass           # the original failure
        finally:
            self._event.set()
        if fr is not None and pm is not None:
            try:
                fr.maybe_dump("partition_failure", pm)
            except Exception:  # noqa: BLE001
                pass

    def done(self) -> bool:
        return self._event.is_set()

    def failed(self) -> bool:
        return self._error is not None

    def error(self) -> Optional[BaseException]:
        """The failure that froze this handle (a
        :class:`PartitionFailure`), or None — the public read for
        callers that classify failures without wait()'s raise (e.g.
        the serve router's retry-vs-terminal migration decision)."""
        return self._error

    def wait(self, timeout: Optional[float] = None) -> Dict[int, Any]:
        # BYTEPS_HANDLE_DEADLINE_MS is a hard ceiling on EVERY wait —
        # including timeout=None callers — so no configuration can turn a
        # dead peer into an infinite block; the expiry is a diagnosable
        # StallError, not a silent hang.
        from byteps_tpu.common.config import get_config

        deadline_ms = get_config().handle_deadline_ms
        effective = timeout
        capped = False
        if deadline_ms and deadline_ms > 0:
            cap_s = deadline_ms / 1e3
            if effective is None or cap_s < effective:
                effective = cap_s
                capped = True
        if not self._event.wait(effective):
            diag = None
            if self.diag is not None:
                try:
                    diag = self.diag()
                except Exception as e:  # noqa: BLE001 - diagnostics are
                    # best-effort; a failing callback must not mask the
                    # stall itself
                    diag = {"diag_error": f"{type(e).__name__}: {e}"}
            with self._lock:
                done = sorted(self.results)
            err = StallError(self.name, effective, done,
                             self._num_partitions, diag,
                             deadline_capped=capped)
            # the always-on flight recorder's post-mortem rides EVERY
            # stall (with or without a pipeline diag callback): the
            # per-step ring + recent FAULT events show the run's shape
            # before the moment of death. The FAULT-ring event is
            # recorded once per handle: poll-style waiters (short
            # timeout in a loop, catching TimeoutError) re-raise this
            # every slice, and per-raise events would evict the genuine
            # retry/failover history the ring exists to keep.
            try:
                fr = get_flight_recorder()
                with self._lock:
                    first = not self._stall_recorded
                    self._stall_recorded = True
                if first:
                    fr.record_event("stall", {
                        "handle": self.name, "done": len(done),
                        "total": self._num_partitions,
                        "deadline_capped": capped})
                err.post_mortem = fr.post_mortem(reason="stall")
            except Exception:  # noqa: BLE001 - telemetry must never
                pass           # mask the stall itself
            raise err
        if self._error is not None:
            raise self._error
        return self.results


@dataclasses.dataclass
class Stage:
    """One pipeline stage (reference analog: one QueueType + its core loop).

    ``fn(task) -> result`` runs the stage. If ``credited`` the stage draws
    from the scheduler's credit budget while the task occupies it (the
    reference applies credits at PUSH). ``pool_size`` > 1 lets slow blocking
    stages (e.g. DCN push/pull waiting on sockets) overlap across partitions.

    ``releases_credit`` scopes the credit to the WIRE, not the pipeline:
    a task's credit frees when it exits this stage instead of at pipeline
    completion. The DCN pipelines set it on PUSH so that — on a slow
    (throttled) link where PULL is as expensive as PUSH — partition i+credit
    can start pushing while partition i is still pulling/decompressing;
    credit then bounds concurrent *push occupancy* (the reference's
    BYTEPS_SCHEDULING_CREDIT bounds bytes in the push queue the same way).
    Default False keeps the hold-until-completion scope (the eager ICI
    pipeline's SYNC stage relies on it: the credit must outlive device-side
    completion, which is what bounds in-flight collectives).

    ``retryable`` re-enqueues a failed task at THIS stage (priority
    preserved — it re-enters the same priority queue) instead of instantly
    failing the whole ``Handle``: up to ``max_attempts`` total tries with
    ``retry_backoff_s`` × 2^n backoff. While backing off, the task's
    credit (if held) is returned to the pool — a partition sleeping out a
    DCN fault must not starve its siblings of the wire — and is
    re-acquired through the normal credited-stage gate when the retry is
    issued. Exceptions carrying ``retryable = False`` (e.g. a total-DCN
    outage) fail immediately. The DCN pipelines set it on PUSH/PULL as the
    second line of defense above the PSWorker wire retries (it is what
    turns a mid-flight failover — FailedOverError — into a re-run against
    the new placement instead of a failed handle).
    """

    name: str
    fn: Callable[["PartitionTask"], Any]
    credited: bool = False
    pool_size: int = 1
    releases_credit: bool = False
    retryable: bool = False
    max_attempts: int = 3
    retry_backoff_s: float = 0.05


@dataclasses.dataclass
class PartitionTask:
    """A partition moving through the pipeline (reference: TensorTableEntry)."""

    partition: Partition
    name: str
    handle: Handle
    payload: Any = None        # stage functions read/replace this
    stage_idx: int = 0
    context: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # The aggregation ROUND this task belongs to (the tensor's version
    # counter at enqueue). Only consulted when the scheduler's
    # ``rounds_window`` is armed (bounded-staleness pipelining): a task
    # may not issue while its key still has a round more than ``window``
    # behind it in flight — the per-key run-ahead bound that generalizes
    # the credit gate from partitions to rounds. None = ungated.
    round: Optional[int] = None
    # perf_counter of the last queue insertion (set by _StageQueue.push):
    # issue_time − queued_at is the stage DWELL the metrics registry
    # tracks per stage — queue wait is the quantity the priority
    # scheduler exists to control
    queued_at: float = 0.0
    # Credit ownership is PER-TASK state and must never live in
    # ``context``: the production pipelines share one context dict across
    # every partition of a tensor, which would let partition 0's credit
    # cover its siblings (and a release refund a credit a sibling holds).
    holds_credit: bool = False
    # The credit POOL this task's credit came from (owner-scoped credits):
    # recorded at acquire time so the release refunds the same pool even
    # if an owner failover re-routes the task's wire mid-flight.
    credit_pool: int = 0
    # Tries consumed at the CURRENT stage (Stage.retryable); reset to 0
    # when the task advances, so each stage gets its own budget.
    stage_attempts: int = 0

    @property
    def sort_key(self):
        # Max-priority first; ties by key (reference sorts by (priority, key)).
        return (-self.partition.priority, self.partition.key)


class _StageQueue:
    """Priority queue for one stage (reference: BytePSScheduledQueue)."""

    def __init__(self) -> None:
        self._heap: List = []
        self._counter = 0

    def push(self, task: PartitionTask) -> None:
        task.queued_at = time.perf_counter()
        self._counter += 1
        heapq.heappush(self._heap, (task.sort_key, self._counter, task))

    def pop(self) -> Optional[PartitionTask]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[2]

    def pop_ready(self, ready) -> Optional[PartitionTask]:
        """Pop the highest-priority task satisfying ``ready``, skipping
        blocked heads (owner-scoped credits: a drained owner's partition
        at the head must not head-of-line-block a sibling owner whose
        NIC still has credits). Skipped items keep their heap position.

        Deliberately a linear scan past the blocked prefix (O(blocked ·
        log n) per issue) rather than per-owner sub-heaps: readiness is
        NOT uniform per owner — a mid-queue task may hold a credit from
        an earlier credited stage, and an owner failover remaps
        partitions while queued — so bucket heads alone can hide a ready
        task. Partition counts are bounded (gradient_bytes /
        partition_bytes, typically ≤ a few hundred) and the scan runs
        only when the head is blocked; revisit if profiles ever show
        this lock hot."""
        skipped = []
        got = None
        while self._heap:
            item = heapq.heappop(self._heap)
            if ready(item[2]):
                got = item[2]
                break
            skipped.append(item)
        for it in skipped:
            heapq.heappush(self._heap, it)
        return got

    def peek(self) -> Optional[PartitionTask]:
        if not self._heap:
            return None
        return self._heap[0][2]

    def __len__(self) -> int:
        return len(self._heap)


class PipelineScheduler:
    """Drives PartitionTasks through stages in priority order under credits.

    One instance per process (the reference had one set of queues+loops per
    GPU process; on TPU one process drives all local devices).
    """

    def __init__(
        self,
        stages: Sequence[Stage],
        credit: int = 4,
        tracer: Optional[TraceRecorder] = None,
        credit_scope: str = "global",
        rounds_window: Optional[int] = None,
    ) -> None:
        """``credit_scope="owner"`` gives each partition OWNER (the pod
        controller whose NIC carries it in sharded-wire hybrid mode) its
        own credit pool of ``credit``: the bound models per-NIC queue
        depth, so one owner's slow/faulted wire backs off only its own
        partitions instead of starving every sibling NIC of issue slots.
        "global" (default) is the single shared pool (one NIC).

        ``rounds_window=K`` (bounded staleness, BYTEPS_STALENESS) arms a
        per-KEY run-ahead bound on top of the credit gate: a task whose
        ``round`` is more than K rounds ahead of its key's oldest
        still-in-flight round is held in its queue — so a pipelining
        caller keeps at most K+1 rounds of one key's pushes in flight
        while PULL consumes whatever round the server serves, and a
        straggler-parked round bounds its own key's memory instead of
        the process's. A round-blocked head is SKIPPED (other keys keep
        flowing); None = ungated (the pre-staleness behavior)."""
        if credit_scope not in ("global", "owner"):
            raise ValueError(f"unknown credit_scope {credit_scope!r}")
        self.stages = list(stages)
        register_stage_order([s.name for s in self.stages])
        # metrics handles resolved ONCE (near-zero hot path: the per-op
        # cost is the metric's own lock + arithmetic, never a name
        # lookup) — docs/observability.md
        _reg = get_registry()
        sid = next(_SCHED_SEQ)
        self._m_run = [_reg.histogram(f"scheduler.stage.{s.name}.run_us")
                       for s in self.stages]
        self._m_dwell = [_reg.histogram(f"scheduler.stage.{s.name}.dwell_us")
                         for s in self.stages]
        self._m_credit_in_use = _reg.gauge(
            f"scheduler.s{sid}.credits_in_use")
        self._m_rounds_inflight = _reg.gauge(
            f"scheduler.s{sid}.rounds_inflight")
        self._m_tasks_done = _reg.counter("scheduler.tasks_done")
        self._m_tasks_failed = _reg.counter("scheduler.tasks_failed")
        self._m_stage_retries = _reg.counter("scheduler.stage_retries")
        self._credits_in_use = 0
        self._queues = [_StageQueue() for _ in self.stages]
        self._credit_total = max(1, credit)
        self._credit_scope = credit_scope
        self._credits = self._credit_total
        # owner scope: pool id -> available credits, created on first use
        self._owner_credits: Dict[int, int] = {}
        # per-key in-flight ROUNDS (rounds_window): key -> set of rounds
        # with at least one task between enqueue and finish
        self._rounds_window = (None if rounds_window is None
                               else max(0, int(rounds_window)))
        self._key_rounds: Dict[int, set] = {}
        self._lock = threading.Lock()
        self._tracer = tracer
        self._pools: List[ThreadPoolExecutor] = [
            ThreadPoolExecutor(
                max_workers=s.pool_size, thread_name_prefix=f"bps-{s.name}"
            )
            for s in self.stages
        ]
        self._busy = [0] * len(self.stages)
        self._shutdown = False
        self._inflight = 0
        self._idle = threading.Condition(self._lock)

    # -- public API ---------------------------------------------------------
    def enqueue(self, tasks: Sequence[PartitionTask]) -> None:
        if self._shutdown:
            raise RuntimeError("PipelineScheduler is shut down")
        with self._lock:
            for t in tasks:
                self._inflight += 1
                if self._rounds_window is not None and t.round is not None:
                    self._key_rounds.setdefault(
                        t.partition.key, set()).add(t.round)
                self._queues[t.stage_idx].push(t)
            self._update_rounds_gauge_locked()
        self._pump()

    def set_credit(self, credit: int) -> None:
        """Adjust total credit (auto-tuner hook); takes effect as credits recycle."""
        with self._lock:
            delta = max(1, credit) - self._credit_total
            self._credit_total = max(1, credit)
            self._credits += delta
            for pool in self._owner_credits:
                self._owner_credits[pool] += delta
        self._pump()

    # -- round-window accounting (call with self._lock held) ----------------
    def _round_ready_locked(self, task: PartitionTask) -> bool:
        """True when ``task`` is within the per-key run-ahead window: its
        round is at most ``rounds_window`` ahead of the oldest round its
        key still has in flight. Unblocks monotonically — rounds only
        LEAVE the in-flight set at finish, so a task that passes here
        keeps passing at every later stage."""
        if self._rounds_window is None or task.round is None:
            return True
        rounds = self._key_rounds.get(task.partition.key)
        if not rounds:
            return True
        return task.round - min(rounds) <= self._rounds_window

    def _retire_round_locked(self, task: PartitionTask) -> None:
        if self._rounds_window is None or task.round is None:
            return
        rounds = self._key_rounds.get(task.partition.key)
        if rounds is not None:
            rounds.discard(task.round)
            if not rounds:
                del self._key_rounds[task.partition.key]
        self._update_rounds_gauge_locked()

    def _update_rounds_gauge_locked(self) -> None:
        if self._rounds_window is None:
            return
        self._m_rounds_inflight.set(
            max((len(r) for r in self._key_rounds.values()), default=0))

    # -- credit accounting (call with self._lock held) ----------------------
    def _credit_available(self, task: PartitionTask) -> bool:
        if self._credit_scope == "global":
            return self._credits > 0
        return self._owner_credits.get(
            task.partition.owner, self._credit_total) > 0

    def _acquire_credit_locked(self, task: PartitionTask) -> None:
        task.holds_credit = True
        self._credits_in_use += 1
        self._m_credit_in_use.set(self._credits_in_use)
        if self._credit_scope == "global":
            task.credit_pool = 0
            self._credits -= 1
            return
        pool = task.partition.owner
        task.credit_pool = pool
        self._owner_credits[pool] = self._owner_credits.get(
            pool, self._credit_total) - 1

    def _release_credit_locked(self, task: PartitionTask) -> None:
        if not task.holds_credit:
            return
        task.holds_credit = False
        self._credits_in_use -= 1
        self._m_credit_in_use.set(self._credits_in_use)
        if self._credit_scope == "global":
            self._credits = min(self._credits + 1, self._credit_total)
            return
        pool = task.credit_pool
        self._owner_credits[pool] = min(
            self._owner_credits.get(pool, self._credit_total) + 1,
            self._credit_total)

    def credit_pools(self) -> Dict[int, int]:
        """Snapshot of available credits per pool (leak assertions): the
        global pool is key 0; owner scope reports every pool touched."""
        with self._lock:
            if self._credit_scope == "global":
                return {0: self._credits}
            return dict(self._owner_credits)

    def drain(self, timeout: Optional[float] = None) -> None:
        with self._idle:
            if not self._idle.wait_for(
                    lambda: self._inflight == 0 or self._shutdown, timeout):
                raise TimeoutError("scheduler drain timed out")
            if self._shutdown:
                # shutdown() failed everything that was in flight; a drain
                # racing it must report that, not pretend a clean flush
                raise RuntimeError("PipelineScheduler was shut down while "
                                   "draining")

    def shutdown(self) -> None:
        """Stop the pipeline. Every queued task's handle is FAILED (so
        ``Handle.wait()`` raises instead of blocking forever on a
        partition that will never run), in-flight tasks fail on stage
        exit, and pending retry timers fail their tasks when they fire."""
        stranded: List[PartitionTask] = []
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            for q in self._queues:
                while True:
                    t = q.pop()
                    if t is None:
                        break
                    stranded.append(t)
                    self._release_credit_locked(t)
            self._inflight -= len(stranded)
            self._key_rounds.clear()  # window state dies with the pipeline
        err = RuntimeError("PipelineScheduler is shut down")
        for t in stranded:
            t.handle._partition_failed(err, t.partition.part_idx)
        with self._idle:
            self._idle.notify_all()
        for p in self._pools:
            p.shutdown(wait=False)

    # -- internals ----------------------------------------------------------
    def _pump(self) -> None:
        """Issue as many ready tasks as credits/pools allow, priority first."""
        while True:
            issued = None
            with self._lock:
                if self._shutdown:
                    return
                for si, stage in enumerate(self.stages):
                    q = self._queues[si]
                    if not len(q):
                        continue
                    if self._busy[si] >= self.stages[si].pool_size:
                        continue
                    # A task acquires at most one credit for its whole
                    # lifetime (reference: credit held from PUSH until the
                    # partition completes); one already holding a credit
                    # passes later credited stages freely. With the
                    # rounds window armed, a round-blocked head is
                    # SKIPPED (its unblockers are earlier rounds in
                    # LATER stages, never behind it in this queue — so
                    # skipping loses no ordering, while head-blocking
                    # would stall sibling keys whose window is open).
                    if self._rounds_window is not None or (
                            stage.credited
                            and self._credit_scope == "owner"):
                        task = q.pop_ready(
                            lambda t: self._round_ready_locked(t)
                            and (not stage.credited or t.holds_credit
                                 or self._credit_available(t)))
                        if task is None:
                            continue
                        if stage.credited and not task.holds_credit:
                            self._acquire_credit_locked(task)
                    else:
                        head = q.peek()
                        needs_credit = (stage.credited
                                        and not head.holds_credit)
                        if needs_credit and not self._credit_available(head):
                            continue
                        task = q.pop()
                        if needs_credit:
                            self._acquire_credit_locked(task)
                    self._busy[si] += 1
                    issued = (si, task)
                    break
            if issued is None:
                return
            si, task = issued
            try:
                self._pools[si].submit(self._run_stage, si, task)
            except RuntimeError as e:
                # shutdown() ran between our pop and this submit: the pool
                # rejects new work. The task is in no queue, so shutdown's
                # strand sweep missed it — fail its handle here or wait()
                # would hang (the exact class of hang shutdown() fixes).
                with self._lock:
                    self._busy[si] -= 1
                self._finish(task, error=RuntimeError(
                    f"PipelineScheduler is shut down ({e})"))
                return

    def _run_stage(self, si: int, task: PartitionTask) -> None:
        stage = self.stages[si]
        t_issue = time.perf_counter()
        if task.queued_at:
            self._m_dwell[si].observe((t_issue - task.queued_at) * 1e6)
        t0 = self._tracer._now_us() if self._tracer else 0.0
        try:
            result = stage.fn(task)
            task.payload = result
            failed = None
        except BaseException as e:  # noqa: BLE001 - propagate via handle
            failed = e
        self._m_run[si].observe((time.perf_counter() - t_issue) * 1e6)
        retrying = (
            failed is not None
            and stage.retryable
            and not self._shutdown
            and task.stage_attempts + 1 < stage.max_attempts
            and getattr(failed, "retryable", True)
        )
        if failed is not None:
            if retrying:
                log.warning(
                    "stage %s failed for %s.%d (attempt %d/%d, will "
                    "retry): %s", stage.name, task.name,
                    task.partition.part_idx, task.stage_attempts + 1,
                    stage.max_attempts, failed)
            else:
                log.error("stage %s failed for %s.%d: %s",
                          stage.name, task.name, task.partition.part_idx,
                          failed)
        if self._tracer:
            self._tracer.complete_event(
                name=f"{task.name}.p{task.partition.part_idx}",
                stage=stage.name,
                start_us=t0,
                dur_us=self._tracer._now_us() - t0,
                args={
                    "key": task.partition.key,
                    "priority": task.partition.priority,
                    "length": task.partition.length,
                    **({"error": type(failed).__name__,
                        "attempt": task.stage_attempts}
                       if failed is not None else {}),
                },
            )
        with self._lock:
            self._busy[si] -= 1
            if failed is None and stage.releases_credit:
                # wire-scoped credit: frees on stage exit so the next
                # partition's push can start while this one drains the
                # rest of the pipeline (_finish's release is then a no-op)
                self._release_credit_locked(task)
            elif retrying:
                # about to back off: a sleeping task must not keep a
                # credit out of the pool (it would starve healthy
                # siblings of the wire). The retry re-acquires through
                # the normal credited-stage gate when it is re-issued.
                self._release_credit_locked(task)
        if retrying:
            task.stage_attempts += 1
            self._m_stage_retries.inc()
            delay = stage.retry_backoff_s * (2 ** (task.stage_attempts - 1))
            if self._tracer:
                self._tracer.instant(
                    f"{task.name}.p{task.partition.part_idx}.retry",
                    stage.name,
                    {"key": task.partition.key,
                     "attempt": task.stage_attempts,
                     "error": type(failed).__name__})
            timer = threading.Timer(delay, self._requeue_retry, (si, task))
            timer.daemon = True
            timer.start()
            self._pump()  # the freed credit may unblock a sibling now
            return
        if failed is not None:
            self._finish(task, error=failed)
        elif si + 1 < len(self.stages):
            task.stage_idx = si + 1
            task.stage_attempts = 0  # fresh budget at the next stage
            with self._lock:
                stranded = self._shutdown
                if not stranded:
                    self._queues[si + 1].push(task)
            if stranded:
                # shutdown() already drained the queues; a task advancing
                # past it must fail its handle, not sit in a dead queue
                self._finish(task, error=RuntimeError(
                    "PipelineScheduler is shut down"))
            else:
                self._pump()
        else:
            self._finish(task)

    def _requeue_retry(self, si: int, task: PartitionTask) -> None:
        """Backoff timer fired: put the task back on its own stage's
        priority queue (its sort key is unchanged, so a high-priority
        retry still jumps the line)."""
        with self._lock:
            if not self._shutdown:
                self._queues[si].push(task)
                task = None  # enqueued; not stranded
        if task is not None:  # raced shutdown(): fail, don't strand
            task.handle._partition_failed(
                RuntimeError("PipelineScheduler is shut down"),
                task.partition.part_idx)
            with self._idle:
                self._inflight -= 1
                self._idle.notify_all()
            return
        self._pump()

    def _finish(self, task: PartitionTask, error: Optional[BaseException] = None) -> None:
        """Reference analog: FinishOrProceed's terminal arm."""
        with self._lock:
            self._release_credit_locked(task)
            self._retire_round_locked(task)
            self._inflight -= 1
        if error is not None:
            self._m_tasks_failed.inc()
            task.handle._partition_failed(error, task.partition.part_idx)
        else:
            self._m_tasks_done.inc()
            task.handle._partition_done(task.partition.part_idx, task.payload)
        with self._idle:
            if self._inflight == 0:
                self._idle.notify_all()
        self._pump()
