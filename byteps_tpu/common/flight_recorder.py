"""Per-step flight recorder: the always-on post-mortem ring buffer.

A stall report used to show only the moment of death: ``StallError``
carried the *current* per-NIC counters and credit pools, but nothing
about the steps leading up to it — was PUSH p99 creeping for 40 rounds,
or did one FAULT event kill the job cold? This module keeps a bounded
ring of per-step metric snapshots (stage dwell/run percentiles, wire
totals, credit occupancy, step walltime) plus the most recent
FAULT-class events, and hands the whole thing out as a **post-mortem**
that rides every ``StallError`` / ``PartitionFailure`` (attached
centrally in ``common/scheduler.py``) and is exposed to bench/tests as
``byteps_tpu.metrics_snapshot()``.

Feeding it costs nothing extra at the producer sites:

* **steps** — the tracer's step advance (``TraceRecorder.advance_to`` /
  ``fused_step`` / ``step``) already fires on every push_pull round and
  every fused train step, on every path (jax eager, jax hybrid,
  DcnCore, torch/tf adapters); the recorder hooks it. Each tick also
  observes ``train.step_ms`` in the registry — train-step walltime is a
  first-class metric, not a bench-only number.
* **events** — every FAULT-track chrome-trace instant (retries,
  failovers, evictions, membership changes, injected faults) is
  forwarded by the tracer REGARDLESS of whether tracing is enabled;
  the flight recorder is the always-on consumer the trace file is the
  opt-in one.

Knobs: ``BYTEPS_FLIGHT_RECORDER_STEPS`` (ring size, 0 disables the
per-step ring), ``BYTEPS_FLIGHT_RECORDER_EVENTS`` (event ring),
``BYTEPS_FLIGHT_RECORDER_DIR`` (also write post-mortems as JSON files,
one per distinct failure reason per run). See docs/observability.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry, json_safe

log = get_logger("flight_recorder")

# µs-scaled buckets would waste the low end on a step-walltime series;
# step times are ms-scale, so give train.step_ms the default ladder
# (1 ms .. 1e8 ms covers everything a real run produces).
_STEP_MS_HIST = "train.step_ms"


class FlightRecorder:
    """Bounded per-step snapshot ring + recent FAULT events."""

    def __init__(self, max_steps: int = 64, max_events: int = 128,
                 dump_dir: str = "") -> None:
        self.max_steps = max(0, max_steps)
        self.max_events = max(0, max_events)
        self._steps: deque = deque(maxlen=max(1, self.max_steps))
        self._events: deque = deque(maxlen=max(1, self.max_events))
        self._dump_dir = dump_dir
        self._lock = threading.Lock()
        # serializes the WHOLE step advance (guard + snapshot + ring
        # append): two concurrent advancers — e.g. a jax host-callback
        # trace marker and the post-dispatch tick — must not interleave
        # their snapshots, or the ring gets out-of-order entries whose
        # counters were sampled from the wrong step. RLock: tick() holds
        # it across its read-then-advance so a racing ticker cannot
        # swallow a step.
        self._step_serial = threading.RLock()
        self._step = 0
        self._last_step_t: Optional[float] = None
        self._t0 = time.time()
        # one post-mortem FILE per distinct reason per run: a shutdown
        # storm failing hundreds of handles must not write hundreds of
        # identical dumps
        self._dumped_reasons: set = set()
        # burst coalescing: per-reason (monotonic time, dict) of the
        # last built post-mortem — a storm failing hundreds of handles
        # in one instant shares ONE dict instead of assembling (and
        # retaining) hundreds of near-identical snapshots
        self._pm_cache: Dict[str, Any] = {}

    # -- producers -----------------------------------------------------------
    def record_event(self, name: str, args: Optional[Dict[str, Any]] = None,
                     ) -> None:
        """A FAULT-class event (fed by the tracer's FAULT-track instants;
        also callable directly). Args are sanitized at record time so a
        numpy scalar can never poison a later JSON dump."""
        if self.max_events <= 0:  # BYTEPS_FLIGHT_RECORDER_EVENTS=0
            return
        ev = {
            "t_s": round(time.time() - self._t0, 6),
            "step": self._step,
            "event": str(name),
            "args": json_safe(args or {}),
        }
        with self._lock:
            self._events.append(ev)

    def on_step(self, step_no: int) -> None:
        """Step boundary (tracer step advance). Snapshots the registry's
        headline series into the ring and observes the step walltime.
        Idempotent per step number; skipped steps collapse into one
        entry (the walltime then covers the skipped span). Serialized
        end to end under ``_step_serial`` so concurrent advancers
        append in step order with step-consistent snapshots."""
        with self._step_serial:
            self._on_step_serialized(step_no)

    def _on_step_serialized(self, step_no: int) -> None:
        now = time.perf_counter()
        with self._lock:
            if step_no <= self._step:
                return
            self._step = step_no
            last = self._last_step_t
            self._last_step_t = now
        step_ms = None if last is None else (now - last) * 1e3
        reg = get_registry()
        if step_ms is not None:
            reg.histogram(_STEP_MS_HIST).observe(step_ms)
        if self.max_steps <= 0:
            return
        # per-step cost must not grow with the process's total
        # histogram count: scalars for everything, percentile scans
        # only for the stage histograms (full snapshot is post_mortem's
        # job, once, at failure time)
        scalars = reg.snapshot_scalars()
        stage_hists = reg.snapshot(prefix="scheduler.stage.")
        # per-step stage view: cumulative dwell/run percentiles at this
        # step (the stall question is "what moved?" — diffing
        # consecutive entries answers it)
        stages: Dict[str, Any] = {}
        for k in stage_hists["histograms"]:
            if not k.endswith(".run_us"):
                continue
            st = k[len("scheduler.stage."):-len(".run_us")]
            stages[st] = {
                "dwell_p50_us": _p(stage_hists,
                                   f"scheduler.stage.{st}.dwell_us", "p50"),
                "dwell_p99_us": _p(stage_hists,
                                   f"scheduler.stage.{st}.dwell_us", "p99"),
                "run_p50_us": _p(stage_hists,
                                 f"scheduler.stage.{st}.run_us", "p50"),
                "run_p99_us": _p(stage_hists,
                                 f"scheduler.stage.{st}.run_us", "p99"),
            }
        entry = {
            "step": step_no,
            "t_s": round(time.time() - self._t0, 6),
            "step_ms": None if step_ms is None else round(step_ms, 3),
            "stages": stages,
            "counters": scalars["counters"],
            "gauges": scalars["gauges"],
        }
        with self._lock:
            self._steps.append(entry)

    def tick(self) -> None:
        """Advance ONE step relative to the recorder's current step —
        for producers with a private notion of "a step happened" (the
        fused train-step wrappers) that cannot know the process-wide
        step number: an absolute ``on_step(local_count)`` from a fresh
        1-based counter would be silently dropped whenever the recorder
        already advanced past it (eager rounds before training, a
        second model in the same process). The read-then-advance holds
        ``_step_serial`` so a racing advancer cannot swallow the tick
        (and its train.step_ms sample)."""
        with self._step_serial:
            with self._lock:
                nxt = self._step + 1
            self._on_step_serialized(nxt)

    # -- consumers -----------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def steps(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._steps)

    def post_mortem(self, reason: str = "manual",
                    extra: Optional[Dict[str, Any]] = None,
                    dump: bool = True,
                    coalesce_s: float = 0.5) -> Dict[str, Any]:
        """The full flight dump: the step ring, the FAULT-event ring, and
        the registry's current snapshot. Attached to StallError /
        PartitionFailure; also written to BYTEPS_FLIGHT_RECORDER_DIR
        (once per reason) when configured and ``dump``. Extra-less calls
        for the same reason within ``coalesce_s`` share ONE dict — a
        shutdown storm failing hundreds of handles must not assemble
        hundreds of near-identical snapshots."""
        now = time.monotonic()
        if extra is None:
            with self._lock:
                cached = self._pm_cache.get(reason)
            if cached is not None and now - cached[0] < coalesce_s:
                return cached[1]
        with self._lock:
            steps = list(self._steps)
            events = list(self._events)
            step = self._step
        pm: Dict[str, Any] = {
            "reason": reason,
            "step": step,
            "steps": steps,
            "fault_events": events,
            "metrics": get_registry().snapshot(),
            # the run's resolved knobs ride every dump: a post-mortem is
            # a valid (degraded) what-if simulator input on its own
            # (sim/extract.cost_model_from_flight_dump)
            "config": _config_snapshot(),
        }
        if extra:
            pm["extra"] = json_safe(extra)
        else:
            with self._lock:
                self._pm_cache[reason] = (now, pm)
        if dump:
            self.maybe_dump(reason, pm)
        return pm

    def summary(self) -> Dict[str, Any]:
        """Light view for metrics_snapshot(): counts, not payloads."""
        with self._lock:
            return {
                "step": self._step,
                "ring_steps": len(self._steps),
                "fault_events": len(self._events),
            }

    def maybe_dump(self, reason: str, pm: Dict[str, Any]) -> Optional[str]:
        """Write ``pm`` as a JSON file into BYTEPS_FLIGHT_RECORDER_DIR
        (no-op when unset; once per reason per run). Public so callers
        that must signal waiters BEFORE touching the disk (scheduler's
        partition-failure path) can split build and dump."""
        if not self._dump_dir:
            return None
        with self._lock:
            if reason in self._dumped_reasons:
                return None
            self._dumped_reasons.add(reason)
        try:
            os.makedirs(self._dump_dir, exist_ok=True)
            path = os.path.join(
                self._dump_dir,
                f"flight_{reason}_{os.getpid()}.json")
            with open(path, "w") as f:
                json.dump(pm, f, indent=1)
            log.warning("flight-recorder post-mortem (%s) written to %s",
                        reason, path)
            return path
        except Exception as e:  # noqa: BLE001 - a post-mortem writer
            # must never add a second failure on top of the first
            log.warning("flight-recorder dump failed: %s", e)
            return None


def _config_snapshot() -> Dict[str, Any]:
    """The resolved Config as a JSON-safe dict; never lets a config
    problem break a post-mortem (telemetry must not add a second
    failure)."""
    try:
        from byteps_tpu.common.config import get_config

        return get_config().snapshot()
    except Exception:  # noqa: BLE001
        return {}


def _p(snap: Dict[str, Any], name: str, stat: str) -> Optional[float]:
    h = snap["histograms"].get(name)
    if not h or not h.get("count"):
        return None
    v = h.get(stat)
    return None if v is None else round(v, 1)


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                from byteps_tpu.common.config import get_config

                cfg = get_config()
                _recorder = FlightRecorder(
                    max_steps=cfg.flight_recorder_steps,
                    max_events=cfg.flight_recorder_events,
                    dump_dir=cfg.flight_recorder_dir,
                )
    return _recorder


def reset_flight_recorder() -> None:
    """Drop the cached recorder (test isolation, like reset_registry)."""
    global _recorder
    with _recorder_lock:
        _recorder = None
