"""Shared worker-side core for host-framework adapters (torch, tensorflow).

Reference analog: the common machinery both ``byteps/torch/ops.cc`` and
``byteps/tensorflow/ops.cc`` call into (``EnqueueTensor`` + queue lists,
``operations.cc``): tensor declaration/partitioning, the credit-scheduled
PUSH/PULL pipeline against the DCN summation servers, and handle assembly.
Framework adapters only convert tensors to/from flat numpy fp32.
"""

from __future__ import annotations

import threading
import zlib
from typing import Optional

import numpy as np

from byteps_tpu.common.config import get_config
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.partition import TensorRegistry
from byteps_tpu.common.scheduler import (
    Handle,
    PartitionTask,
    PipelineScheduler,
    Stage,
)
from byteps_tpu.common.tracing import get_tracer
from byteps_tpu.compression.wire import Fp16Wire, WireCodec, WirePlan
from byteps_tpu.server import NoLiveServersError, PSWorker

log = get_logger("dcn_adapter")


class DegradedLocal:
    """Marker payload riding PULL when the whole DCN tier is dead: carries
    the encoded LOCAL contribution through the pipeline so DECOMPRESS
    yields this worker's own sum instead of the cross-worker one —
    graceful degradation (BYTEPS_DEGRADED_OK) rather than a failed handle.
    Shared with the jax hybrid pipeline, where the local contribution is
    the pod's pure-ICI sum."""

    __slots__ = ("payload",)

    def __init__(self, payload):
        self.payload = payload


def degraded_fallback(worker, cfg, task, adapter_log, what: str):
    """Shared no-live-servers gate for the PUSH stages (DcnCore + jax
    hybrid): raises fail-fast when BYTEPS_DEGRADED_OK is off, else counts
    the fallback, warns once, and wraps the task's payload (the encoded
    LOCAL contribution) in :class:`DegradedLocal`.

    Degradation is recorded PER PARTITION: ``handle.degraded_parts`` maps
    part_idx -> (offset, length). A handle can be mixed — earlier
    partitions aggregated globally before the last server died — so
    averaging consumers (torch/tf synchronize, the jax COPYH2D stage)
    must scale slice-by-slice: global slices divide by the global size,
    degraded slices by the LOCAL participant count the fallback could
    actually reach."""
    p = task.partition
    if not cfg.degraded_ok:
        err = NoLiveServersError(
            f"push {task.name}.{p.part_idx}: no live summation servers "
            "(BYTEPS_DEGRADED_OK=0)")
        # fail-fast: a stage retry cannot help when degrading is forbidden
        err.retryable = False
        raise err
    worker._count("ici_fallbacks")
    if worker.counters["ici_fallbacks"] == 1:
        adapter_log.warning(
            "no live summation servers: degrading push_pull to %s "
            "(BYTEPS_DEGRADED_OK)", what)
    task.degraded = True  # DECOMPRESS decodes the PUSH-side encoding
    with task.handle._lock:
        parts = getattr(task.handle, "degraded_parts", None)
        if parts is None:
            parts = {}
            task.handle.degraded_parts = parts
        parts[p.part_idx] = (p.offset, p.length)
    return DegradedLocal(task.payload)


def wire_codec_for(compression: Optional[str]) -> Optional[WireCodec]:
    """Map a host adapter's ``Compression`` choice onto a DCN wire codec
    (reference: byteps/torch/compression.py — fp16 halves actual wire
    bytes, it is not a round-trip simulation)."""
    if compression in (None, "none", ""):
        return None
    if compression == "fp16":
        return Fp16Wire()
    raise ValueError(f"unknown compression {compression!r}; "
                     "host adapters support 'none' or 'fp16'")


class DcnCore:
    """One per process; drives flat fp32 buffers through the DCN pipeline.

    Stages mirror the reference queue list around the wire
    (``core_loops.cc`` COMPRESS → PUSH → PULL → DECOMPRESS): codec work
    runs on its own pool so chunk i+1 compresses WHILE chunk i is on the
    wire — on a throttled/slow DCN the codec time hides entirely behind
    transmission instead of serializing with it. The credit is acquired
    at COMPRESS and released when the chunk leaves PUSH
    (``releases_credit`` wire scope): at most ``credit`` encoded
    payloads exist at once — a slow link cannot make the compress pool
    buffer every partition's encoded bytes — overlap survives whenever
    credit ≥ 2 (default 4), and slow pulls never starve later pushes.
    """

    def __init__(self, servers=None, worker_id=None) -> None:
        cfg = get_config()
        self.cfg = cfg
        self.worker = PSWorker(servers=servers, worker_id=worker_id)
        self.registry = TensorRegistry()
        # PUSH/PULL are stage-retryable: the second line of defense above
        # PSWorker's wire retries — a mid-flight failover (FailedOverError)
        # re-runs the stage against the new placement with a fresh round
        # number instead of failing the Handle.
        self.scheduler = PipelineScheduler(
            stages=[
                Stage("COMPRESS", self._compress_stage, credited=True,
                      pool_size=2),
                Stage("PUSH", self._push_stage, credited=True, pool_size=4,
                      releases_credit=True, retryable=True),
                Stage("PULL", self._pull_stage, pool_size=4,
                      retryable=True),
                Stage("DECOMPRESS", self._decompress_stage, pool_size=2),
            ],
            credit=cfg.scheduling_credit,
            tracer=get_tracer(),
        )
        self._inited_keys = set()
        self._key_lock = threading.Lock()
        self._versions = {}
        self.worker.barrier()

    @staticmethod
    def _wire_seed(name: str, version: int, part_idx: int) -> int:
        """Deterministic per (tensor, round, partition) codec seed, agreed
        across workers (same derivation as the jax hybrid pipeline)."""
        base = zlib.crc32(name.encode()) & 0xFFFFFFFF
        return (base * 1000003 + version * 8191 + part_idx) % (2 ** 63)

    # -- stages -------------------------------------------------------------
    def _compress_stage(self, task: PartitionTask):
        """Wire encode on the codec pool (reference COMPRESS stage) —
        decoupled from PUSH so the encode of chunk i+1 overlaps the wire
        time of chunk i."""
        p = task.partition
        flat: np.ndarray = task.context["flat"]
        # fp32 coercion here, not at push: the registry declared float32
        # and the store was sized at length*4 — a float64/int caller
        # must be converted, never byte-viewed at the wrong width
        chunk = np.ascontiguousarray(
            flat[p.offset:p.offset + p.length], np.float32)
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        if plan is None:
            return chunk.view(np.uint8).ravel()
        return plan.codec.encode(
            chunk,
            self._wire_seed(task.name, task.context["version"], p.part_idx),
        )

    def _push_stage(self, task: PartitionTask):
        p = task.partition
        if not self.worker.has_live_servers():
            # total DCN outage: degrade to the local contribution instead
            # of failing the handle (docs/robustness.md)
            return degraded_fallback(self.worker, self.cfg, task, log,
                                     "LOCAL sums")
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        store_bytes = (
            plan.codec.store_elems(p.length) * 4 if plan is not None
            else p.length * 4
        )
        with self._key_lock:
            needs_init = p.key not in self._inited_keys
            if needs_init:
                self._inited_keys.add(p.key)
        if needs_init:
            # no cross-worker barrier needed: server-side init is idempotent
            # and never resets an existing store, so only THIS worker's init
            # must precede its own push (serial on this connection)
            self.worker.init_key(p.key, store_bytes)
        codec_id = plan.codec.codec_id if plan is not None else 0
        # pin the round across STAGE retries: a re-run whose first try's
        # push WAS applied (wire budget exhausted on lost acks) must
        # re-send the same version for the server dedupe to recognize it;
        # push_bytes discards a pin that predates a failover reset
        version = self.worker.push_bytes(
            p.key, task.payload, codec_id,
            version=getattr(task, "push_version", None))
        task.push_version = version
        return version

    def _pull_stage(self, task: PartitionTask):
        p = task.partition
        if isinstance(task.payload, DegradedLocal):
            return task.payload.payload  # DECOMPRESS decodes the local sum
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        capacity = (plan.pull_capacity(p.length) if plan is not None
                    else p.length * 4)
        codec_id = plan.pull_codec_id if plan is not None else 0
        return self.worker.pull_bytes(p.key, capacity, task.payload, codec_id)

    def _decompress_stage(self, task: PartitionTask):
        """Wire decode of the pulled round result (reference DECOMPRESS),
        again off the wire pool so decodes overlap later chunks' pulls."""
        p = task.partition
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        buf = np.ascontiguousarray(task.payload)
        seed = self._wire_seed(task.name, task.context["version"],
                               p.part_idx)
        if plan is None:
            return buf.view(np.float32)
        if getattr(task, "degraded", False):
            # degraded payload is the PUSH-side encoding (the pull wire
            # format never existed for this round)
            return plan.codec.decode(buf, p.length, seed)
        return plan.decode_pull(buf, p.length, seed)

    # -- public -------------------------------------------------------------
    def push_pull_async(self, flat: np.ndarray, name: str,
                        priority: Optional[int] = None,
                        codec: Optional[WireCodec] = None,
                        two_way: bool = True) -> Handle:
        """Enqueue a flat fp32 vector; returns a Handle whose results are
        per-partition summed numpy chunks. ``codec`` compresses the DCN wire
        per partition (the server decodes, fp32-sums, re-encodes);
        partitions below BYTEPS_MIN_COMPRESS_BYTES ride raw fp32, matching
        the jax hybrid pipeline and the reference's
        BYTEPS_MIN_COMPRESS_BYTES semantics."""
        ctx = self.registry.declare(name, (flat.size,), np.float32)
        with self._key_lock:
            version = self._versions.get(name, 0)
            self._versions[name] = version + 1
        # auto step detection, as on the jax eager path: the highest round
        # any tensor reached IS the training step — BYTEPS_TRACE_ON=1
        # alone records the host adapters' stage spans, no user code
        get_tracer().advance_to(version + 1)
        plans = [
            None
            if codec is None or p.length * 4 < self.cfg.min_compress_bytes
            else WirePlan(codec, two_way)
            for p in ctx.partitions
        ]
        handle = Handle(name, len(ctx.partitions))
        shared = {"flat": flat, "plans": plans, "version": version}
        tasks = []
        for p in ctx.partitions:
            if priority is not None:
                p = type(p)(key=p.key, tensor_id=p.tensor_id,
                            part_idx=p.part_idx, offset=p.offset,
                            length=p.length, priority=priority)
            tasks.append(PartitionTask(partition=p, name=name, handle=handle,
                                       context=shared))
        self.scheduler.enqueue(tasks)
        return handle

    @staticmethod
    def assemble(handle: Handle, timeout: Optional[float] = 120.0) -> np.ndarray:
        results = handle.wait(timeout)
        parts = [results[i] for i in sorted(results)]
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def shutdown(self) -> None:
        self.scheduler.shutdown()
        self.worker.shutdown()