"""Shared worker-side core for host-framework adapters (torch, tensorflow).

Reference analog: the common machinery both ``byteps/torch/ops.cc`` and
``byteps/tensorflow/ops.cc`` call into (``EnqueueTensor`` + queue lists,
``operations.cc``): tensor declaration/partitioning, the credit-scheduled
PUSH/PULL pipeline against the DCN summation servers, and handle assembly.
Framework adapters only convert tensors to/from flat numpy fp32.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from byteps_tpu.common.config import get_config
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.partition import TensorRegistry
from byteps_tpu.common.scheduler import (
    Handle,
    PartitionTask,
    PipelineScheduler,
    Stage,
)
from byteps_tpu.common.tracing import get_tracer
from byteps_tpu.server import PSWorker

log = get_logger("dcn_adapter")


class DcnCore:
    """One per process; drives flat fp32 buffers through PUSH/PULL."""

    def __init__(self) -> None:
        cfg = get_config()
        self.cfg = cfg
        self.worker = PSWorker()
        self.registry = TensorRegistry()
        self.scheduler = PipelineScheduler(
            stages=[
                Stage("PUSH", self._push_stage, credited=True, pool_size=4),
                Stage("PULL", self._pull_stage, pool_size=4),
            ],
            credit=cfg.scheduling_credit,
            tracer=get_tracer(),
        )
        self._inited_keys = set()
        self._key_lock = threading.Lock()
        self.worker.barrier()

    # -- stages -------------------------------------------------------------
    def _push_stage(self, task: PartitionTask):
        p = task.partition
        flat: np.ndarray = task.context["flat"]
        chunk = np.ascontiguousarray(flat[p.offset:p.offset + p.length])
        with self._key_lock:
            needs_init = p.key not in self._inited_keys
            if needs_init:
                self._inited_keys.add(p.key)
        if needs_init:
            # no cross-worker barrier needed: server-side init is idempotent
            # and never resets an existing store, so only THIS worker's init
            # must precede its own push (serial on this connection)
            self.worker.init_key(p.key, p.length * 4)
        return self.worker.push(p.key, chunk)

    def _pull_stage(self, task: PartitionTask):
        p = task.partition
        return self.worker.pull(p.key, p.length, task.payload)

    # -- public -------------------------------------------------------------
    def push_pull_async(self, flat: np.ndarray, name: str,
                        priority: Optional[int] = None) -> Handle:
        """Enqueue a flat fp32 vector; returns a Handle whose results are
        per-partition summed numpy chunks."""
        ctx = self.registry.declare(name, (flat.size,), np.float32)
        handle = Handle(name, len(ctx.partitions))
        shared = {"flat": flat}
        tasks = []
        for p in ctx.partitions:
            if priority is not None:
                p = type(p)(key=p.key, tensor_id=p.tensor_id,
                            part_idx=p.part_idx, offset=p.offset,
                            length=p.length, priority=priority)
            tasks.append(PartitionTask(partition=p, name=name, handle=handle,
                                       context=shared))
        self.scheduler.enqueue(tasks)
        return handle

    @staticmethod
    def assemble(handle: Handle, timeout: Optional[float] = 120.0) -> np.ndarray:
        results = handle.wait(timeout)
        parts = [results[i] for i in sorted(results)]
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def shutdown(self) -> None:
        self.scheduler.shutdown()
        self.worker.shutdown()