"""Shared worker-side core for host-framework adapters (torch, tensorflow).

Reference analog: the common machinery both ``byteps/torch/ops.cc`` and
``byteps/tensorflow/ops.cc`` call into (``EnqueueTensor`` + queue lists,
``operations.cc``): tensor declaration/partitioning, the credit-scheduled
PUSH/PULL pipeline against the DCN summation servers, and handle assembly.
Framework adapters only convert tensors to/from flat numpy fp32.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from byteps_tpu.common.config import get_config
from byteps_tpu.common.faults import FaultPlan, parse_fault_spec
from byteps_tpu.common.logging import bps_check, get_logger
from byteps_tpu.common.partition import OwnerTable, TensorRegistry
from byteps_tpu.common.scheduler import (
    Handle,
    PartitionTask,
    PipelineScheduler,
    Stage,
)
from byteps_tpu.common.stage_orders import (  # noqa: F401 - re-exported;
    # the canonical orders live in the light leaf module so
    # trace_analysis can learn them without importing the data plane
    DCN_STAGE_ORDER,
    EAGER_STAGE_ORDER,
    HYBRID_STAGE_ORDER,
)
from byteps_tpu.common.tracing import get_tracer
from byteps_tpu.compression.wire import (
    Fp16Wire,
    WireCodec,
    WirePlan,
    pull_seed,
    wire_seed,
)
from byteps_tpu.server import (
    FailedOverError,
    NoLiveServersError,
    PSWorker,
    WorkerEvictedError,
    hand_off_owner,
    retire_nic,
)

log = get_logger("dcn_adapter")


def owner_wire_death(e: BaseException) -> bool:
    """Classify a stage-level wire failure as the OWNER's NIC dying
    (sharded-wire mode): a connection-class error that still escaped the
    PSWorker retry engine means every wire attempt through that owner's
    connections failed — the common element is the owner's NIC, so remap
    its partitions to the surviving controllers. Server-side conditions
    (failover in progress, no live servers, a server-down window that
    outlasted the wire retry budget) are explicitly NOT owner deaths: the
    existing health-monitor/failover/degraded paths own those.
    ServerDownError names the SERVER as the culprit — remapping would let
    one slow-to-detect server outage serially (and irreversibly) kill
    every healthy controller routing at it; the stage retry it gets
    instead rides out the window or the health monitor trips first.
    TimeoutError and CRC-detected WireCorruption are excluded for the
    same reason: a recv timeout blames a slow-but-alive SERVER at least
    as plausibly as the local NIC (a dead NIC resurfaces as a
    refused/reset reconnect, i.e. ConnectionError, on the next attempt),
    corrupt payloads blame the server/path that produced them, and
    failover is irreversible while a stage retry costs one backoff."""
    from byteps_tpu.common.faults import ServerDownError

    if isinstance(e, (NoLiveServersError, FailedOverError,
                      ServerDownError)):
        return False
    return isinstance(e, ConnectionError)


def remap_dead_owner(task, owner: int, owners, fail_owner, owner_of,
                     cause: BaseException, verb: str):
    """Shared owner-failover CLIENT policy (DcnCore and the jax hybrid
    pipeline both route here): fail ``owner`` over — or piggyback on a
    sibling task's earlier failover of the same rank, which ``fail_owner``
    reports as False exactly like the last-controller case — and raise
    the stage-retryable remap error so the re-run resolves a survivor.
    Returns without raising only when no survivor exists (last
    controller): the caller's degraded/terminal path decides."""
    failed = fail_owner(owner, cause)
    if failed or owner not in owners.live():
        err = RuntimeError(
            f"owner {owner} {verb} for {task.name}."
            f"{task.partition.part_idx}; remapped — retrying via owner "
            f"{owner_of(task.partition.key)}")
        err.retryable = True
        raise err from cause


def stall_diag(workers, owners, scheduler):
    """Assemble a ``Handle.diag`` payload — ONE definition shared by
    DcnCore and the jax hybrid tier, so StallError reports from the two
    pipelines never drift: per-NIC robustness/health counters, live
    server/owner sets, and the scheduler's credit/busy state (what a
    stall report needs to show WHY retry/failover did or didn't fire)."""
    return {
        "workers": {f"nic{r}": w.get_counters()
                    for r, w in enumerate(workers)},
        "wire_bytes": {f"nic{r}": {"pushed": w.bytes_pushed,
                                   "pulled": w.bytes_pulled}
                       for r, w in enumerate(workers)},
        "live_servers": {f"nic{r}": sorted(w.live_servers())
                         for r, w in enumerate(workers)},
        "live_owners": (sorted(owners.live())
                        if owners is not None else None),
        "credit_pools": (scheduler.credit_pools()
                         if scheduler is not None else None),
        "stage_busy": ({s.name: b for s, b in
                        zip(scheduler.stages, scheduler._busy)}
                       if scheduler is not None else None),
    }


class DegradedLocal:
    """Marker payload riding PULL when the whole DCN tier is dead: carries
    the encoded LOCAL contribution through the pipeline so DECOMPRESS
    yields this worker's own sum instead of the cross-worker one —
    graceful degradation (BYTEPS_DEGRADED_OK) rather than a failed handle.
    Shared with the jax hybrid pipeline, where the local contribution is
    the pod's pure-ICI sum."""

    __slots__ = ("payload",)

    def __init__(self, payload):
        self.payload = payload


def degraded_fallback(worker, cfg, task, adapter_log, what: str):
    """Shared no-live-servers gate for the PUSH stages (DcnCore + jax
    hybrid): raises fail-fast when BYTEPS_DEGRADED_OK is off, else counts
    the fallback, warns once, and wraps the task's payload (the encoded
    LOCAL contribution) in :class:`DegradedLocal`.

    Degradation is recorded PER PARTITION: ``handle.degraded_parts`` maps
    part_idx -> (offset, length). A handle can be mixed — earlier
    partitions aggregated globally before the last server died — so
    averaging consumers (torch/tf synchronize, the jax COPYH2D stage)
    must scale slice-by-slice: global slices divide by the global size,
    degraded slices by the LOCAL participant count the fallback could
    actually reach."""
    p = task.partition
    if not cfg.degraded_ok:
        err = NoLiveServersError(
            f"push {task.name}.{p.part_idx}: no live summation servers "
            "(BYTEPS_DEGRADED_OK=0)")
        # fail-fast: a stage retry cannot help when degrading is forbidden
        err.retryable = False
        raise err
    worker._count("ici_fallbacks")
    if worker.counters["ici_fallbacks"] == 1:
        adapter_log.warning(
            "no live summation servers: degrading push_pull to %s "
            "(BYTEPS_DEGRADED_OK)", what)
    task.degraded = True  # DECOMPRESS decodes the PUSH-side encoding
    with task.handle._lock:
        parts = getattr(task.handle, "degraded_parts", None)
        if parts is None:
            parts = {}
            task.handle.degraded_parts = parts
        parts[p.part_idx] = (p.offset, p.length)
    return DegradedLocal(task.payload)


def wire_codec_for(compression: Optional[str]) -> Optional[WireCodec]:
    """Map a host adapter's ``Compression`` choice onto a DCN wire codec
    (reference: byteps/torch/compression.py — fp16 halves actual wire
    bytes, it is not a round-trip simulation)."""
    if compression in (None, "none", ""):
        return None
    if compression == "fp16":
        return Fp16Wire()
    raise ValueError(f"unknown compression {compression!r}; "
                     "host adapters support 'none' or 'fp16'")


class DcnCore:
    """One per process; drives flat fp32 buffers through the DCN pipeline.

    Stages mirror the reference queue list around the wire
    (``core_loops.cc`` COMPRESS → PUSH → PULL → DECOMPRESS): codec work
    runs on its own pool so chunk i+1 compresses WHILE chunk i is on the
    wire — on a throttled/slow DCN the codec time hides entirely behind
    transmission instead of serializing with it. The credit is acquired
    at COMPRESS and released when the chunk leaves PUSH
    (``releases_credit`` wire scope): at most ``credit`` encoded
    payloads exist at once — a slow link cannot make the compress pool
    buffer every partition's encoded bytes — overlap survives whenever
    credit ≥ 2 (default 4), and slow pulls never starve later pushes.
    """

    def __init__(self, servers=None, worker_id=None,
                 pod_controllers: Optional[int] = None,
                 fault_specs: Optional[Sequence[Optional[str]]] = None,
                 health_interval_ms: Optional[int] = None,
                 ) -> None:
        """``pod_controllers`` > 1 turns on the sharded-wire hierarchical
        mode (BytePS "use every link"): the pod is modeled as that many
        controllers, each with its own PSWorker — its own connections,
        pacer-emulated NIC, and fault plan — and each partition is
        COMPRESSed/PUSHed/PULLed only by its rendezvous-hashed owner, so
        per-NIC DCN bytes divide by the controller count. Default: the
        config's BYTEPS_POD_CONTROLLERS when BYTEPS_HYBRID_SHARDED, else
        1 (identical to the classic single-NIC core). ``fault_specs``
        optionally arms a per-OWNER fault plan (chaos tests kill one
        owner's NIC while its siblings stay healthy)."""
        cfg = get_config()
        self.cfg = cfg
        if pod_controllers is None:
            pod_controllers = (max(1, cfg.pod_controllers)
                               if cfg.hybrid_sharded else 1)
        plans: List[Optional[FaultPlan]] = [None] * pod_controllers
        if fault_specs is not None:
            bps_check(
                len(fault_specs) == pod_controllers,
                f"fault_specs needs one entry per controller "
                f"(got {len(fault_specs)} for {pod_controllers})")
            plans = [
                FaultPlan(parse_fault_spec(s), seed=cfg.fault_seed,
                          worker_id=o) if s else None
                for o, s in enumerate(fault_specs)
            ]
        # All of a pod's controllers push under the POD's worker_id: the
        # server sees one contribution per pod per round per key (from
        # whichever controller owns it), and replay dedupe — which is
        # keyed (worker, key, version) — survives an owner remap because
        # the surviving controller adopts the round counters and re-sends
        # under the same pod id (PSWorker.adopt_rounds).
        self.workers: List[PSWorker] = [
            PSWorker(servers=servers, worker_id=worker_id,
                     fault_plan=plans[o],
                     health_interval_ms=health_interval_ms)
            for o in range(pod_controllers)
        ]
        self.worker = self.workers[0]  # back-compat accounting handle
        self.owners = OwnerTable(pod_controllers, salt=cfg.owner_salt)
        self._owner_lock = threading.Lock()
        self.owner_failovers = 0
        self.registry = TensorRegistry()
        # PUSH/PULL are stage-retryable: the second line of defense above
        # PSWorker's wire retries — a mid-flight failover (FailedOverError)
        # re-runs the stage against the new placement with a fresh round
        # number instead of failing the Handle. Sharded pods scope credits
        # per owner: each NIC gets its own in-flight bound, so one faulted
        # owner backing off cannot starve its siblings' wires.
        stages = [
            Stage("COMPRESS", self._compress_stage, credited=True,
                  pool_size=2),
            # +1 attempt per extra controller: a total-DCN-outage
            # walk-down spends one stage attempt failing each owner
            # over before the last controller may degrade
            Stage("PUSH", self._push_stage, credited=True, pool_size=4,
                  releases_credit=True, retryable=True,
                  max_attempts=2 + pod_controllers),
            Stage("PULL", self._pull_stage, pool_size=4,
                  retryable=True, max_attempts=2 + pod_controllers),
            Stage("DECOMPRESS", self._decompress_stage, pool_size=2),
        ]
        # pinned against the declared order trace_analysis sorts by — a
        # stage added here without updating DCN_STAGE_ORDER is a bug
        bps_check(
            tuple(s.name for s in stages) == DCN_STAGE_ORDER,
            "DcnCore stage list drifted from DCN_STAGE_ORDER")
        self.scheduler = PipelineScheduler(
            stages=stages,
            credit=cfg.scheduling_credit,
            tracer=get_tracer(),
            credit_scope="owner" if pod_controllers > 1 else "global",
            # bounded staleness (BYTEPS_STALENESS=K): a pipelining caller
            # may keep K+1 rounds of one key in flight — PUSH of round
            # r+K no longer gates on round r's PULL, the server serves
            # whatever closed round is within K, and the window bounds
            # the run-ahead (the credit gate generalized to rounds)
            rounds_window=cfg.staleness if cfg.staleness > 0 else None,
        )
        # keys each OWNER has successfully init'ed on the servers: a new
        # owner (post-failover) must re-run the idempotent init before
        # its first push of an inherited key
        self._inited_keys: Dict[int, Set[int]] = {
            o: set() for o in range(pod_controllers)}
        self._key_lock = threading.Lock()
        self._versions = {}
        self.worker.barrier()

    # -- sharded-wire ownership --------------------------------------------
    def _owner_of(self, key: int) -> int:
        return self.owners.owner(key)

    def fail_owner(self, rank: int,
                   cause: Optional[BaseException] = None) -> bool:
        """Mark controller ``rank`` dead and remap its partitions to the
        survivors (fence → export → adopt → shrink; the ordering argument
        lives on :func:`byteps_tpu.server.hand_off_owner`). EF/momentum-style
        per-owner state does not exist on this host core; the jax hybrid
        pipeline resets its own on the matching event. Returns False if
        already dead or it is the last controller (then the normal
        degraded/no-live-servers machinery decides)."""
        with self._owner_lock:
            if hand_off_owner(self.workers, self.owners, rank) is None:
                return False
            self.owner_failovers += 1
        if rank != 0:
            # free the dead NIC (health monitor thread, connections,
            # pacer) — nothing routes through it again. Worker 0 stays
            # open, fenced: it alone may carry the pod's single kShutdown
            # round at teardown (servers count one goodbye per pod). Its
            # counters (the retries/injected faults that killed it) fold
            # into the trace first — close() alone would drop them.
            retire_nic(self.workers[rank], rank)
        get_tracer().instant("owner_failover", "FAULT",
                             {"owner": rank,
                              "survivors": sorted(self.owners.live()),
                              "cause": type(cause).__name__ if cause
                              else None})
        log.warning(
            "pod controller %d gave up its wire (%s); its partitions "
            "remap to owners %s", rank,
            cause if cause is not None else "requested",
            sorted(self.owners.live()))
        return True

    def _owner_giveup(self, task: PartitionTask, owner: int,
                      e: BaseException):
        """A retry-exhausted wire error through ``owner``'s NIC: fail the
        owner over and turn the error into a stage-retryable one so the
        scheduler re-runs the stage, which re-resolves to a survivor."""
        if len(self.workers) > 1 and owner_wire_death(e):
            remap_dead_owner(task, owner, self.owners, self.fail_owner,
                             self._owner_of, e, "wire dead")
        raise e

    # -- stages -------------------------------------------------------------
    def _compress_stage(self, task: PartitionTask):
        """Wire encode on the codec pool (reference COMPRESS stage) —
        decoupled from PUSH so the encode of chunk i+1 overlaps the wire
        time of chunk i."""
        p = task.partition
        flat: np.ndarray = task.context["flat"]
        # fp32 coercion here, not at push: the registry declared float32
        # and the store was sized at length*4 — a float64/int caller
        # must be converted, never byte-viewed at the wrong width
        chunk = np.ascontiguousarray(
            flat[p.offset:p.offset + p.length], np.float32)
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        if plan is None:
            return chunk.view(np.uint8).ravel()
        return plan.codec.encode(
            chunk,
            wire_seed(task.name, task.context["version"], p.part_idx),
        )

    def _push_stage(self, task: PartitionTask):
        p = task.partition
        owner = self._owner_of(p.key)
        worker = self.workers[owner]
        if not worker.has_live_servers():
            # THIS NIC sees zero live servers. Each PSWorker's health
            # monitor pings through its own connections, so with sibling
            # NICs alive this is indistinguishable from the OWNER's link
            # dying — fail the owner over first (a sibling's view may be
            # healthy; degrading here would silently turn this
            # partition's result pod-LOCAL while other pods keep global
            # sums). A genuine total outage walks the owners down to the
            # last controller, which then degrades as before.
            if len(self.workers) > 1:
                remap_dead_owner(
                    task, owner, self.owners, self.fail_owner,
                    self._owner_of,
                    NoLiveServersError(
                        f"owner {owner} sees no live servers"),
                    "lost all servers")
            # total DCN outage: degrade to the local contribution instead
            # of failing the handle (docs/robustness.md)
            return degraded_fallback(worker, self.cfg, task, log,
                                     "LOCAL sums")
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        store_bytes = (
            plan.codec.store_elems(p.length) * 4 if plan is not None
            else p.length * 4
        )
        with self._key_lock:
            needs_init = p.key not in self._inited_keys[owner]
        try:
            if needs_init:
                # no cross-worker barrier needed: server-side init is
                # idempotent and never resets an existing store, so only
                # this owner's init must precede its own push (serial on
                # its connection). Marked inited only AFTER success — a
                # failed init retried at the stage level must re-run, not
                # be skipped forever (two racing pushes of one key both
                # initing is harmless, again by idempotence).
                worker.init_key(p.key, store_bytes)
                with self._key_lock:
                    self._inited_keys[owner].add(p.key)
            codec_id = plan.codec.codec_id if plan is not None else 0
            # pin the round BEFORE the wire attempt (mint_version): a
            # stage retry — including one re-routed to a surviving owner
            # after a failover — must re-send the SAME round, whether the
            # first try was applied (ack lost: the server dedupe drops
            # the re-send) or never arrived (the server is still waiting
            # for exactly this round). Minting inside push_bytes would
            # lose the number when the attempt throws, and the retry's
            # fresh mint would stall the server's round sequence forever.
            # A pin predating a server-failover counter reset is
            # discarded (fresh round against the new placement).
            task.push_version = worker.mint_version(
                p.key, getattr(task, "push_version", None))
            version = worker.push_bytes(
                p.key, task.payload, codec_id,
                version=task.push_version)
        except BaseException as e:  # noqa: BLE001 - owner-death classify
            if isinstance(e, WorkerEvictedError):
                # the pinned round predates the eviction; the rejoin
                # (already performed by the retry loop) adopted the
                # server's watermarks, so the stage retry must mint a
                # FRESH round — a stale pin at/below the watermark would
                # be silently dedupe-dropped (permanent per-key stall)
                task.push_version = None
            self._owner_giveup(task, owner, e)
        task.push_version = version
        return version

    def _pull_stage(self, task: PartitionTask):
        p = task.partition
        if isinstance(task.payload, DegradedLocal):
            return task.payload.payload  # DECOMPRESS decodes the local sum
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        capacity = (plan.pull_capacity(p.length) if plan is not None
                    else p.length * 4)
        codec_id = plan.pull_codec_id if plan is not None else 0
        owner = self._owner_of(p.key)
        try:
            out = self.workers[owner].pull_bytes(
                p.key, capacity, task.payload, codec_id)
        except BaseException as e:  # noqa: BLE001 - owner-death classify
            self._owner_giveup(task, owner, e)
        # the round the server actually SERVED (== requested on the
        # strict-sync tier; up to BYTEPS_STALENESS behind under bounded
        # staleness) — DECOMPRESS derives its seed from it, so a stale
        # aggregate decodes with the round it was BUILT from
        task.served_round = self.workers[owner].last_pull_round()
        # record the round's OWN live count per partition (from the
        # response's epoch stamp) so averaging consumers (torch/tf
        # synchronize) divide each slice by the membership its round
        # actually closed under — handles can be MIXED across an
        # eviction, exactly like degraded_parts
        live = self.workers[owner].last_round_live()
        if live is not None:
            with task.handle._lock:
                parts = getattr(task.handle, "part_live", None)
                if parts is None:
                    parts = {}
                    task.handle.part_live = parts
                parts[p.part_idx] = (p.offset, p.length, live)
        return out

    def _decompress_stage(self, task: PartitionTask):
        """Wire decode of the pulled round result (reference DECOMPRESS),
        again off the wire pool so decodes overlap later chunks' pulls."""
        p = task.partition
        plan: Optional[WirePlan] = task.context["plans"][p.part_idx]
        buf = np.ascontiguousarray(task.payload)
        # the served round may trail the requested one under bounded
        # staleness — pull_seed owns the served-round → seed contract
        seed = pull_seed(
            task.name, task.context["version"], p.part_idx,
            served_round=getattr(task, "served_round", None),
            staleness=self.cfg.staleness,
            degraded=getattr(task, "degraded", False))
        if plan is None:
            return buf.view(np.float32)
        if getattr(task, "degraded", False):
            # degraded payload is the PUSH-side encoding (the pull wire
            # format never existed for this round)
            return plan.codec.decode(buf, p.length, seed)
        return plan.decode_pull(buf, p.length, seed)

    # -- elasticity ---------------------------------------------------------
    def join(self) -> int:
        """Mid-stream scale-UP: run the kJoin admission handshake on
        every controller NIC (:meth:`PSWorker.join` — admission + round-
        watermark adoption, all NICs under the pod's shared worker id),
        so a fresh or previously-evicted pod enters a running job at a
        round boundary. Returns the adopted live pod count — what the
        caller's data-shard reassignment and LR/batch rescale hooks
        consume (``data.ElasticShardMap``, ``jax.linear_scale``)."""
        for w in self.workers:
            w.join()
        return self.live_size()

    # -- observability ------------------------------------------------------
    def live_size(self) -> int:
        """Live worker (pod) count per the most recently adopted
        membership epoch — the divisor averaging consumers use instead of
        the static DMLC_NUM_WORKER under elastic membership. Min over the
        pod's NICs: they converge on the same epoch, and between
        adoptions the smaller view is the safe (already-shrunk) one."""
        return max(1, min(w.live_pods() for w in self.workers))

    def _stall_diag(self):
        """Handle.diag callback (shared assembly: :func:`stall_diag`)."""
        return stall_diag(self.workers, self.owners, self.scheduler)

    # -- public -------------------------------------------------------------
    def push_pull_async(self, flat: np.ndarray, name: str,
                        priority: Optional[int] = None,
                        codec: Optional[WireCodec] = None,
                        two_way: bool = True) -> Handle:
        """Enqueue a flat fp32 vector; returns a Handle whose results are
        per-partition summed numpy chunks. ``codec`` compresses the DCN wire
        per partition (the server decodes, fp32-sums, re-encodes);
        partitions below BYTEPS_MIN_COMPRESS_BYTES ride raw fp32, matching
        the jax hybrid pipeline and the reference's
        BYTEPS_MIN_COMPRESS_BYTES semantics."""
        ctx = self.registry.declare(name, (flat.size,), np.float32)
        with self._key_lock:
            version = self._versions.get(name, 0)
            self._versions[name] = version + 1
        # auto step detection, as on the jax eager path: the highest round
        # any tensor reached IS the training step — BYTEPS_TRACE_ON=1
        # alone records the host adapters' stage spans, no user code
        get_tracer().advance_to(version + 1)
        plans = [
            None
            if codec is None or p.length * 4 < self.cfg.min_compress_bytes
            else WirePlan(codec, two_way)
            for p in ctx.partitions
        ]
        handle = Handle(name, len(ctx.partitions))
        handle.diag = self._stall_diag  # StallError diagnostics
        shared = {"flat": flat, "plans": plans, "version": version}
        tasks = []
        for p in ctx.partitions:
            # owner label = placement at enqueue time (credit-pool
            # identity / trace attribution); live routing re-resolves per
            # stage so a failover mid-flight moves the wire anyway
            p = dataclasses.replace(
                p, owner=self._owner_of(p.key),
                **({"priority": priority} if priority is not None else {}))
            tasks.append(PartitionTask(partition=p, name=name, handle=handle,
                                       context=shared, round=version))
        self.scheduler.enqueue(tasks)
        return handle

    @staticmethod
    def assemble(handle: Handle, timeout: Optional[float] = 120.0) -> np.ndarray:
        results = handle.wait(timeout)
        parts = [results[i] for i in sorted(results)]
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def bytes_moved(self):
        """(pushed, pulled) summed over every controller NIC."""
        return (sum(w.bytes_pushed for w in self.workers),
                sum(w.bytes_pulled for w in self.workers))

    def shutdown(self) -> None:
        self.scheduler.shutdown()
        # one kShutdown round per pod, not per controller: servers count
        # shutdowns against DMLC_NUM_WORKER and every controller shares
        # the pod's worker id — the extra NICs retire (counters folded
        # into the trace under a per-NIC tag, sockets dropped)
        for rank, w in enumerate(self.workers[1:], start=1):
            retire_nic(w, rank)
        self.worker.shutdown()
