"""Deterministic DCN fault injection (``BYTEPS_FAULT_SPEC``).

The reference stack survives real DCN weather — slow servers, dropped
connections, stragglers — because ps-lite carries retry/resend machinery
under BytePS. Our port needs the matching *emulated failure surface* so the
self-healing data plane (PSWorker retries, scheduler stage retries, health
failover) can be exercised deterministically on loopback: same philosophy
as the PR-1 bandwidth pacer (``server/pacer.py``) — application-level, no
root/netem/tc, one plan per PSWorker, reproducible from a seed.

Spec grammar (semicolon-separated rules)::

    BYTEPS_FAULT_SPEC = rule (';' rule)*
    rule   = scope ':' kind ['@' cond (',' cond)*]
    scope  = 'push' | 'pull' | 'init' | 'all' | 'server<N>' | 'worker'
           | 'worker<N>' | 'replica' | 'replica<N>' | 'tenant<T>'
           | 'proc' | 'proc<N>'
             # push/pull/all match DATA-PLANE ops only ('all' = push+pull);
             # 'init' matches key-init attempts only (kill = the init
             # never reached the server; timeout = applied, ack lost);
             # server<N> matches every op against that server, including
             # init and the health monitor's pings; 'worker' targets THIS
             # worker process itself (peer-death simulation): kill = the
             # worker dies at that plan op (every later op fails
             # WorkerKilledError, heartbeats stop — the server lease
             # evicts it); hang = the worker wedges for ms= milliseconds
             # (ops block then time out, heartbeats stop) and then may
             # rejoin; worker<N> is the worker scope RESTRICTED to the
             # plan whose worker_id is N — the same spec string is handed
             # to every worker, so 'worker1:slow@ms=80' makes exactly
             # worker 1 a deterministic straggler (every one of its wire
             # attempts pays 80 ms) while its peers run clean — the
             # bounded-staleness bench's slow-worker leg; 'replica' /
             # 'replica<N>' are the SERVE-tier twins: they match only
             # the serve scheduler's per-iteration intercept (op
             # 'serve'), never wire ops, so one spec string handed to
             # every component kills/wedges/slows exactly one serve
             # replica (replica<N> requires the plan's worker_id == N)
             # — the disaggregation tests' deterministic
             # decode-target-death and mid-migration-death legs
             # (docs/serving.md §disaggregation); 'tenant<T>' is the
             # multi-tenant twin: it matches only tenant-ATTRIBUTED
             # serve intercepts (the scheduler's admission attempts
             # for tenant T, made only when tenant rules exist), kinds
             # slow|hang only — 'tenant3:slow@ms=40' makes exactly
             # tenant 3's admissions pay 40 ms while its siblings run
             # clean, the deterministic noisy-tenant flood leg
             # (docs/serving.md §multi-tenant); 'proc' / 'proc<N>' are
             # the LAUNCHER-SUPERVISOR twins (byteps_tpu/launcher.py):
             # they match only the supervisor's per-child plan tick (op
             # 'proc', one tick per Supervisor.poll per child), never
             # wire or serve ops — and unlike every emulated kind the
             # supervisor executes them as REAL OS signals against real
             # child processes: kill = SIGKILL the child (its silence
             # trips the server lease eviction exactly as a real crash
             # would), restart = SIGKILL + respawn through the bounded
             # restart-with-backoff path; proc<N> requires the child
             # plan's worker_id == N, same convention as worker<N>
    kind   = 'timeout' | 'kill' | 'slow' | 'corrupt' | 'down' | 'hang'
           | 'join' | 'restart'
             # 'restart' (proc/proc<N> scopes only): the supervisor
             # SIGKILLs the child and immediately respawns it (counted
             # against the restart budget) — the crash-resume drill
             # 'join' (worker/worker<N> scopes only, deterministic —
             # requires step=, no p=): the worker runs the kJoin
             # mid-stream admission handshake (PSWorker.join: admission
             # + round-watermark adoption) once, when its plan step
             # first enters the window, then the intercepted op
             # proceeds under the adopted membership — the churn
             # bench/tests schedule deterministic mid-stream joins with
             # 'worker<N>:join@step=A'
    cond   = 'p=' FLOAT          # per-op Bernoulli (seeded RNG)
           | 'op=' A ['..' [B]]  # plan-op window, inclusive; open end ok
           | 'step=' ...         # alias of op=
           | 'ms=' INT           # slow/hang: injected latency
                                 # (default 50 slow / 300000 hang)

Examples: ``push:timeout@p=0.05`` — 5% of push attempts lose their
response; ``server1:down@step=40..55`` — every op against server 1 fails
while the plan step is in [40, 55]; ``pull:corrupt@p=0.01`` — 1% of pull
responses get a byte flipped (the CRC32 in the wire frame detects it and
the retry engine re-pulls).

Semantics the consumers rely on:

* **step/op counter** — ticks once per *intercepted wire attempt*
  (including retries), per plan. This is what makes a transient ``down``
  window survivable by pure retry/backoff: each failed attempt advances
  the counter, so a 15-step window expires after at most ~15 attempts
  even when nothing else makes progress. It is NOT the training step.
* **timeout** — the op is performed for real and only then reported as a
  recv timeout (models a lost *response*: the server applied the push).
  This is the path that proves the server's (worker, key, version) replay
  dedupe — the retry re-sends a push the server already summed.
* **kill** — the op never happens (connection dies before the request
  leaves); the injector kills the live socket so the next attempt
  reconnects.
* **corrupt** — a byte of the payload is flipped *after* the CRC was
  computed (push) or *before* it is verified (pull), so the corruption is
  always detected, never silently summed.
* **down** — every op in scope fails with a connection error while the
  window is active (and the socket is killed), emulating a dead/unreachable
  server process.

Determinism: one ``random.Random(seed * 1000003 + worker_id)`` per plan,
advanced only by probability rules, under a lock. Single-threaded
workloads replay exactly; multi-threaded ones are reproducible up to op
interleaving (same as the reference's real network, minus the physics).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from byteps_tpu.common.logging import get_logger

log = get_logger("faults")

__all__ = [
    "FaultRule", "FaultPlan", "Injection", "InjectedTimeout",
    "InjectedConnectionError", "ServerDownError", "WorkerKilledError",
    "parse_fault_spec", "rules_to_spec", "plan_from_env", "churn_events",
]

KINDS = ("timeout", "kill", "slow", "corrupt", "down", "hang", "join",
         "restart")
SCOPES = ("push", "pull", "all", "init", "worker", "replica", "tenant",
          "proc")


class InjectedTimeout(TimeoutError):
    """Injected recv timeout — the response (not the request) was lost."""


class InjectedConnectionError(ConnectionError):
    """Injected connection kill — the request never reached the server."""


class ServerDownError(ConnectionError):
    """Injected server-down window: the server is unreachable."""


class WorkerKilledError(RuntimeError):
    """Injected worker death (``worker:kill``): THIS worker process is
    simulated dead — every wire op fails with this error and heartbeats
    stop, so the server's lease eviction fires exactly as it would for a
    real crash. Never retryable: a dead process retries nothing."""

    retryable = False


@dataclasses.dataclass(frozen=True)
class FaultRule:
    scope: str                 # one of SCOPES, or 'server<N>'
    kind: str                  # one of KINDS
    p: Optional[float] = None  # per-op probability (None = always/window)
    window: Optional[Tuple[int, Optional[int]]] = None  # [a, b] op window
    latency_ms: int = 50       # for kind == 'slow' / 'hang'
    server: Optional[int] = None  # parsed from 'server<N>' scopes
    # parsed from 'worker<N>' / 'replica<N>' / 'proc<N>' scopes: the
    # rule only fires on the plan whose worker_id is N (the shared spec
    # string selects ONE worker/replica/child); None = the bare scope,
    # every plan
    worker: Optional[int] = None
    # parsed from 'tenant<T>' scopes (serve tier, docs/serving.md
    # §multi-tenant): the rule fires only on tenant-attributed serve
    # intercepts whose tenant id stringifies to T — never on the
    # replica-level per-iteration intercept (tenant=None), so a spec
    # carrying both replica and tenant rules keeps each family's step
    # windows independent
    tenant: Optional[str] = None

    def to_spec(self) -> str:
        """Render back to the BYTEPS_FAULT_SPEC grammar (round-trip:
        ``parse_fault_spec(rule.to_spec())`` reproduces the rule)."""
        conds = []
        if self.p is not None:
            conds.append(f"p={self.p}")
        if self.window is not None and self.window != (0, None):
            a, b = self.window
            conds.append(f"op={a}" if b == a else
                         f"op={a}.." + ("" if b is None else str(b)))
        if self.latency_ms != (300000 if self.kind == "hang" else 50):
            conds.append(f"ms={self.latency_ms}")
        if self.scope == "tenant":
            head = f"tenant{self.tenant}:{self.kind}"
        elif (self.scope in ("worker", "replica", "proc")
                and self.worker is not None):
            head = f"{self.scope}{self.worker}:{self.kind}"
        else:
            head = f"{self.scope}:{self.kind}"
        return head + ("@" + ",".join(conds) if conds else "")

    def matches(self, op: str, sidx: int, step: int, rng,
                worker_id: Optional[int] = None,
                tenant: Optional[str] = None) -> bool:
        if self.server is not None:
            # server scopes hit EVERY op against that server — data plane,
            # init, and the health monitor's pings (that is what lets a
            # 'down' window trip the monitor)
            if sidx != self.server:
                return False
        elif self.scope == "worker":
            # worker scopes simulate THIS process's death/wedge/slowness,
            # so they match every wire attempt regardless of target
            # server or op; a worker<N> scope additionally requires the
            # plan to BE worker N (per-worker straggler targeting)
            if self.worker is not None and worker_id != self.worker:
                return False
        elif self.scope == "replica":
            # replica scopes target ONE serve replica's scheduler loop
            # (op 'serve', ticked once per Scheduler.step) and nothing
            # else — a spec string shared with PSWorkers/wires can
            # never make the data plane pay a replica's death; they
            # also never fire on tenant-ATTRIBUTED intercepts, so
            # mixing replica and tenant rules in one spec keeps the
            # replica rules' step-window pins stable
            if op != "serve" or tenant is not None:
                return False
            if self.worker is not None and worker_id != self.worker:
                return False
        elif self.scope == "tenant":
            # tenant scopes fire ONLY on tenant-attributed serve
            # intercepts (the scheduler's admission attempts for that
            # tenant, and only when the plan carries tenant rules at
            # all — so tenant-free specs never see extra step ticks)
            if op != "serve" or tenant is None:
                return False
            # the grammar lowercases the whole rule head, so tenant
            # ids match case-insensitively
            if tenant.lower() != self.tenant:
                return False
        elif self.scope == "proc":
            # proc scopes target ONE supervised child process's plan
            # tick (op 'proc', ticked once per Supervisor.poll) and
            # nothing else — a spec string shared with PSWorkers/wires
            # can never make the data plane pay a process kill, and a
            # child's own in-process plan never sees op 'proc' (the
            # SUPERVISOR owns these plans: a SIGKILLed process cannot
            # execute its own death)
            if op != "proc":
                return False
            if self.worker is not None and worker_id != self.worker:
                return False
        elif self.scope == "init":
            if op != "init":
                return False
        else:
            # push/pull/all scopes are DATA-PLANE only: loss specs must
            # not make the health monitor count injected ping misses and
            # fail over perfectly healthy servers
            if op not in ("push", "pull"):
                return False
            if self.scope != "all" and self.scope != op:
                return False
        if self.window is not None:
            a, b = self.window
            if step < a or (b is not None and step > b):
                return False
        if self.p is not None and rng.random() >= self.p:
            return False
        return True


@dataclasses.dataclass
class Injection:
    """What the interceptor decided for one wire attempt."""

    kind: str
    rule: FaultRule
    # for 'corrupt': which payload byte to flip (modulo the buffer size)
    corrupt_at: int = 0


def _parse_num(value: str, cast, what: str):
    """Cast a condition value, naming the grammar on failure instead of
    leaking a bare ``invalid literal for int()``."""
    try:
        return cast(value)
    except ValueError:
        raise ValueError(
            f"{what} (got {value!r}; grammar: docs/robustness.md)"
        ) from None


def parse_fault_spec(spec: str) -> List[FaultRule]:
    rules: List[FaultRule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            head, _, conds = part.partition("@")
            scope, _, kind = head.partition(":")
            scope = scope.strip().lower()
            kind = kind.strip().lower()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (expected one of "
                    f"{'|'.join(KINDS)})")
            server = None
            worker = None
            tenant = None
            if scope.startswith("tenant"):
                ident = scope[len("tenant"):]
                if not ident:
                    raise ValueError(
                        "tenant scopes need the tenant id inline "
                        "(expected tenant<T>, e.g. tenant3:slow)")
                tenant = ident
                scope = "tenant"
            elif scope.startswith("server") and scope not in SCOPES:
                idx = scope[len("server"):]
                if not idx.isdigit():
                    # 'serverX:down' / 'server:down' must name the
                    # grammar, not surface a bare int() ValueError
                    raise ValueError(
                        f"bad server index {idx!r} in scope {scope!r} "
                        "(expected server<N>, e.g. server1)")
                server = int(idx)
            elif scope.startswith("worker") and scope not in SCOPES:
                idx = scope[len("worker"):]
                if not idx.isdigit():
                    raise ValueError(
                        f"bad worker index {idx!r} in scope {scope!r} "
                        "(expected worker<N>, e.g. worker1)")
                worker = int(idx)
                scope = "worker"
            elif scope.startswith("replica") and scope not in SCOPES:
                idx = scope[len("replica"):]
                if not idx.isdigit():
                    raise ValueError(
                        f"bad replica index {idx!r} in scope {scope!r} "
                        "(expected replica<N>, e.g. replica1)")
                worker = int(idx)
                scope = "replica"
            elif scope.startswith("proc") and scope not in SCOPES:
                idx = scope[len("proc"):]
                if not idx.isdigit():
                    raise ValueError(
                        f"bad proc index {idx!r} in scope {scope!r} "
                        "(expected proc<N>, e.g. proc1)")
                worker = int(idx)
                scope = "proc"
            elif scope not in SCOPES:
                raise ValueError(
                    f"unknown fault scope {scope!r} (expected one of "
                    f"{'|'.join(SCOPES)}, server<N>, worker<N>, "
                    "replica<N>, or proc<N>)")
            if scope == "proc" and kind not in ("kill", "restart"):
                raise ValueError(
                    "proc scopes take only kill|restart — the launcher "
                    "supervisor executes them as REAL signals against a "
                    "child process (kill = SIGKILL, restart = SIGKILL + "
                    "respawn); emulated wire weather belongs to the "
                    "child's own in-process plan")
            if kind == "restart" and scope != "proc":
                raise ValueError(
                    "'restart' is a supervisor action (SIGKILL + "
                    "respawn) and only takes the 'proc'/'proc<N>' "
                    "scopes (proc1:restart@p=0.1)")
            if kind == "hang" and scope not in ("worker", "replica",
                                                "tenant"):
                raise ValueError(
                    "'hang' simulates a worker/replica wedging and only "
                    "takes the 'worker'/'worker<N>'/'replica'/"
                    "'replica<N>'/'tenant<T>' scopes (worker:hang@...)")
            if scope == "tenant" and kind not in ("slow", "hang"):
                raise ValueError(
                    "tenant scopes take only slow|hang — a tenant is "
                    "traffic, not a process: it can be throttled "
                    "(slow = injected latency on its admission, hang = "
                    "its admission defers while the window is active) "
                    "but has no socket to kill or payload to corrupt")
            if scope == "replica" and kind not in ("kill", "hang", "slow"):
                raise ValueError(
                    "replica scopes take only kill|hang|slow — a serve "
                    "replica's step has no payload to corrupt or "
                    "response to lose (wire-leg faults belong to the "
                    "KVWire's own plan)")
            if kind == "join" and scope != "worker":
                raise ValueError(
                    "'join' is a mid-stream worker admission and only "
                    "takes the 'worker'/'worker<N>' scopes "
                    "(worker2:join@step=12)")
            p = None
            window = None
            latency_ms = 300000 if kind == "hang" else 50
            for cond in filter(None, (c.strip() for c in conds.split(","))):
                k, _, v = cond.partition("=")
                k = k.strip().lower()
                v = v.strip()
                if k == "p":
                    p = _parse_num(v, float,
                                   "p= needs a float probability")
                elif k in ("op", "step"):
                    a, dots, b = v.partition("..")
                    lo = _parse_num(a, int, f"{k}= needs an int op index")
                    hi = None if (dots and not b.strip()) else (
                        _parse_num(b, int, f"{k}= window end needs an int")
                        if dots else lo)
                    window = (lo, hi)
                elif k == "ms":
                    latency_ms = _parse_num(
                        v, int, "ms= needs an int millisecond latency")
                else:
                    raise ValueError(
                        f"unknown fault condition {k!r} (expected "
                        "p=|op=|step=|ms=)")
            if kind == "join" and (window is None or p is not None):
                # joins are a deterministic SCHEDULE, not weather: the
                # churn harness derives thread start/stop from the
                # windows, so a probabilistic or bare join is a spec bug
                raise ValueError(
                    "'join' fires deterministically: give a step= "
                    "window (e.g. worker2:join@step=12), not p=")
            if p is None and window is None:
                # bare rule: always fires (e.g. 'server1:down')
                window = (0, None)
            rules.append(FaultRule(scope=scope, kind=kind, p=p,
                                   window=window, latency_ms=latency_ms,
                                   server=server, worker=worker,
                                   tenant=tenant))
        except ValueError as e:
            raise ValueError(
                f"bad BYTEPS_FAULT_SPEC rule {part!r}: {e}") from None
    return rules


def rules_to_spec(rules: List[FaultRule]) -> str:
    """Inverse of :func:`parse_fault_spec` (each rule via
    :meth:`FaultRule.to_spec`) — pinned by the grammar round-trip test."""
    return ";".join(r.to_spec() for r in rules)


def churn_events(rules: List[FaultRule]) -> List[Tuple[int, int, str]]:
    """The deterministic membership SCHEDULE encoded by a spec's
    worker-scoped ``join``/``kill`` rules: ``[(step, worker_id, kind)]``
    sorted by window start. This is what a churn harness (the
    ``bench.py --mode chaos`` churn leg, elasticity tests) drives worker
    thread start/stop from — the same string each worker's plan parses,
    read once at the orchestration layer."""
    out = [
        (r.window[0], r.worker if r.worker is not None else -1, r.kind)
        for r in rules
        if r.scope == "worker" and r.kind in ("join", "kill")
        and r.window is not None
    ]
    return sorted(out)


class FaultPlan:
    """Seeded, per-worker fault schedule over the PSWorker wire boundary.

    One plan per PSWorker: ``intercept(op, sidx)`` is called once per wire
    attempt (push/pull/ping, retries included); it ticks the plan step,
    evaluates every rule, counts what fired, and returns at most one
    :class:`Injection` (first matching rule wins; ``slow`` additionally
    sleeps inline and keeps looking, so latency can compose with a loss).
    """

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 worker_id: int = 0):
        from byteps_tpu.common.metrics import get_registry

        self.rules = list(rules)
        self.seed = seed
        self.worker_id = worker_id
        self._rng = random.Random(seed * 1000003 + worker_id)
        self._lock = threading.Lock()
        self._step = 0
        self.injected: Dict[str, int] = {k: 0 for k in KINDS}
        # always-on registry mirror: per-plan counts die with the plan's
        # PSWorker (owner failover retires it); the process-wide
        # faults.injected_* totals do not (docs/observability.md)
        _reg = get_registry()
        self._m_injected = {k: _reg.counter(f"faults.injected_{k}")
                            for k in KINDS}

    @property
    def step(self) -> int:
        return self._step

    def has_tenant_rules(self) -> bool:
        """True when the spec carries any ``tenant<T>:`` rule — the
        serve scheduler only makes tenant-attributed intercept calls
        (which tick the step counter) when this is set, so tenant-free
        specs keep their historical step-window alignment."""
        return any(r.scope == "tenant" for r in self.rules)

    def intercept(self, op: str, sidx: int,
                  tenant: Optional[str] = None) -> Optional[Injection]:
        """Decide the fate of one wire attempt; sleeps for 'slow' rules."""
        sleep_ms = 0
        hit: Optional[Injection] = None
        with self._lock:
            self._step += 1
            for r in self.rules:
                if not r.matches(op, sidx, self._step, self._rng,
                                 worker_id=self.worker_id,
                                 tenant=tenant):
                    continue
                if r.kind == "slow":
                    self.injected["slow"] += 1
                    self._m_injected["slow"].inc()
                    sleep_ms += r.latency_ms
                    continue  # latency composes with a later loss rule
                self.injected[r.kind] += 1
                self._m_injected[r.kind].inc()
                hit = Injection(kind=r.kind, rule=r,
                                corrupt_at=self._rng.randrange(1 << 30))
                break
        if sleep_ms:
            time.sleep(sleep_ms / 1e3)
        return hit

    @staticmethod
    def corrupt(buf, at: int) -> None:
        """Flip one byte of a writable uint8 buffer in place."""
        if len(buf) == 0:
            return
        i = at % len(buf)
        buf[i] = (int(buf[i]) ^ 0xFF) & 0xFF

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.injected)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"FaultPlan(seed={self.seed}, worker={self.worker_id}, "
                f"rules={self.rules})")


def plan_from_env(cfg=None, worker_id: int = 0) -> Optional[FaultPlan]:
    """FaultPlan from BYTEPS_FAULT_SPEC / BYTEPS_FAULT_SEED, or None."""
    if cfg is None:
        from byteps_tpu.common.config import get_config

        cfg = get_config()
    spec = getattr(cfg, "fault_spec", "")
    if not spec:
        return None
    plan = FaultPlan(parse_fault_spec(spec),
                     seed=getattr(cfg, "fault_seed", 0),
                     worker_id=worker_id)
    log.info("fault injection armed for worker %d: %s", worker_id, spec)
    return plan
