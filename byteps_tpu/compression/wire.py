"""Host-side (numpy) wire codecs for the DCN parameter-server tier.

Reference analog: the worker half of byteps's compression feature — the
COMPRESS/DECOMPRESS stages around PUSH/PULL in
``byteps/common/core_loops.cc``, whose byte formats the server
(``byteps/server/server.cc``) decompresses, fp32-sums, and re-compresses.
The byte layouts here must match ``server/csrc/codec.cc`` bit-exactly; the
formats are documented in ``server/csrc/codec.h``.

These are deliberately *numpy* (host) implementations: the hybrid pipeline's
COMPRESS stage runs after COPYD2H on scheduler pool threads, off the TPU —
the Pallas/jnp compressors in this package serve the fused ICI tier instead.
Stochastic choices (randomk support, dithering rounding) derive only from a
caller-supplied integer seed so every pod agrees where it must.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Optional

import numpy as np

from byteps_tpu.compression.error_feedback import CompressionSpec


def wire_seed(name: str, version: int, part_idx: int, salt: int = 0) -> int:
    """THE deterministic per-(tensor, round, partition) codec seed.

    Every party that encodes or decodes a given partition round — the jax
    hybrid COMPRESS/DECOMPRESS stages on every pod, DcnCore's host
    stages, and (positionally) the summation server — must draw stochastic
    codec choices (randomk support, dithering rounding) from the SAME
    seed, or payloads stop being summable. This is the single definition
    of that contract (it used to live twice, computing different seeds);
    ``salt`` carries a CompressionSpec's user seed where one exists.
    zlib.crc32 is stable across processes/runs, unlike salted hash().
    """
    base = zlib.crc32(name.encode()) & 0xFFFFFFFF
    return (base * 1000003 + version * 8191 + part_idx + salt) % (2 ** 63)


def pull_seed(name: str, context_version: int, part_idx: int,
              served_round=None, staleness: int = 0,
              degraded: bool = False, salt: int = 0) -> int:
    """Seed for decoding a PULLED round result — the one place that owns
    the served-round → version-counter contract under bounded staleness
    (BYTEPS_STALENESS): server round N was pushed at version counter
    N−1, so a seed-keyed pull decode (randomk's positional store) must
    use the seed of the round the served aggregate was BUILT from, not
    the round the caller asked for. K=0 leaves served == requested and
    the seed bit-identical to the sync tier; a DEGRADED payload is the
    PUSH-side encoding of the caller's own round, so it keeps the
    caller's version."""
    v = context_version
    if staleness > 0 and served_round and not degraded:
        v = served_round - 1
    return wire_seed(name, v, part_idx, salt=salt)

# Codec ids — must match server/csrc/codec.h Codec enum.
WIRE_RAW = 0
WIRE_FP16 = 1
WIRE_ONEBIT = 2
WIRE_TOPK = 3
WIRE_DITHER = 4
WIRE_FP8 = 5

_DITHER_NATURAL = 0x1
_DITHER_MAXNORM = 0x2


class WireCodec:
    """Encode/decode one partition for the DCN wire (fp32 both ends)."""

    codec_id = WIRE_RAW

    def encode(self, x: np.ndarray, seed: int = 0) -> np.ndarray:
        """fp32 vector -> uint8 wire bytes."""
        return np.ascontiguousarray(x, np.float32).view(np.uint8).ravel()

    def decode(self, buf: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
        """uint8 wire bytes -> fp32 vector of length n."""
        return np.ascontiguousarray(buf[: n * 4]).view(np.float32).copy()

    def store_elems(self, n: int) -> int:
        """Dense fp32 elements the server must allocate for this key."""
        return n

    def wire_bytes(self, n: int) -> int:
        return n * 4


class Fp16Wire(WireCodec):
    """IEEE binary16 wire — halves every push/pull byte (the reference's
    fp16 Compression shim, byteps/torch/compression.py, with real wire
    savings rather than a round-trip simulation)."""

    codec_id = WIRE_FP16

    def encode(self, x: np.ndarray, seed: int = 0) -> np.ndarray:
        return (
            np.ascontiguousarray(x, np.float32)
            .astype(np.float16)
            .view(np.uint8)
            .ravel()
        )

    def decode(self, buf: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
        return (
            np.ascontiguousarray(buf[: n * 2])
            .view(np.float16)
            .astype(np.float32)
        )

    def wire_bytes(self, n: int) -> int:
        return n * 2


class Fp8Wire(WireCodec):
    """[f32 scale][n bytes e4m3fn] — quarter of raw fp32, half of fp16.
    scale = absmax/448 (1.0 for an all-zero partition); elements are
    clipped to the finite e4m3 range before the ml_dtypes RNE cast so
    the overflow->NaN cast semantics can never fire. Byte-exact C++
    twin in server/csrc/codec.cc."""

    codec_id = WIRE_FP8

    FP8_MAX = 448.0

    def encode(self, x: np.ndarray, seed: int = 0) -> np.ndarray:
        import ml_dtypes

        xf = np.ascontiguousarray(x, np.float32)
        absmax = float(np.max(np.abs(xf))) if xf.size else 0.0
        scale = np.float32(absmax / self.FP8_MAX if absmax > 0 else 1.0)
        q = np.clip(xf / scale, -self.FP8_MAX, self.FP8_MAX)
        body = q.astype(ml_dtypes.float8_e4m3fn).view(np.uint8)
        out = np.empty(4 + xf.size, np.uint8)
        out[:4] = np.frombuffer(scale.tobytes(), np.uint8)
        out[4:] = body
        return out

    def decode(self, buf: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
        import ml_dtypes

        buf = np.ascontiguousarray(buf)
        scale = buf[:4].view(np.float32)[0]
        vals = buf[4:4 + n].view(ml_dtypes.float8_e4m3fn).astype(np.float32)
        return vals * scale

    def wire_bytes(self, n: int) -> int:
        return 4 + n


class OnebitWire(WireCodec):
    """[f32 scale][ceil(n/32) u32 words]; bit (i&31) of word i>>5 set means
    x[i] >= +0.0 (signbit semantics, so -0.0 encodes negative)."""

    codec_id = WIRE_ONEBIT

    def __init__(self, scaling: bool = True):
        self.scaling = bool(scaling)

    def encode(self, x: np.ndarray, seed: int = 0) -> np.ndarray:
        xf = np.ascontiguousarray(x, np.float32)
        n = xf.size
        scale = np.float32(np.mean(np.abs(xf)) if self.scaling and n else 1.0)
        bits = ~np.signbit(xf)
        nwords = (n + 31) // 32
        packed = np.packbits(bits, bitorder="little")
        words = np.zeros(nwords * 4, np.uint8)
        words[: packed.size] = packed
        out = np.empty(4 + nwords * 4, np.uint8)
        out[:4] = np.frombuffer(np.float32(scale).tobytes(), np.uint8)
        out[4:] = words
        return out

    def decode(self, buf: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
        buf = np.ascontiguousarray(buf)
        scale = buf[:4].view(np.float32)[0]
        bits = np.unpackbits(buf[4:], bitorder="little")[:n]
        return np.where(bits, scale, -scale).astype(np.float32)

    def wire_bytes(self, n: int) -> int:
        return 4 + 4 * ((n + 31) // 32)


class TopkWire(WireCodec):
    """[u32 count][count u32 indices][count f32 values]; server
    scatter-adds. The count header makes the format self-describing, so
    every selection strategy shares one decode and one server path:

    * ``selection="exact"`` (default) — argpartition, count = k pairs.
    * ``selection="block"`` — blockwise top-1 (the fused TPU path's
      selection, ``topk.py``): count = rows (can be < k on ragged
      chunks), keeping wire bytes consistent with
      ``TopkCompressor.compressed_bytes``.
    * ``selection="approx"`` — TPU-only selection strategy
      (``lax.approx_max_k`` has no host analog); the wire uses exact
      selection at the identical k-pair budget, which can only improve
      recall.
    """

    codec_id = WIRE_TOPK

    def __init__(self, k=0.01, selection: str = "exact"):
        if selection not in ("exact", "block", "approx"):
            raise ValueError(f"unknown wire selection {selection!r} — "
                             "expected 'exact', 'block', or 'approx'")
        self.k = k
        # approx is TPU-only; on the host wire it aliases exact (same
        # k-pair budget, strictly better recall)
        self.selection = "exact" if selection == "approx" else selection

    def _k(self, n: int) -> int:
        from byteps_tpu.compression.topk import resolve_k

        return resolve_k(self.k, n)

    def _block_shape(self, n: int):
        from byteps_tpu.compression.topk import block_shape

        return block_shape(self.k, n)

    def encode(self, x: np.ndarray, seed: int = 0) -> np.ndarray:
        xf = np.ascontiguousarray(x, np.float32)
        n = xf.size
        if self.selection == "block":
            # must mirror TopkCompressor's TPU-shaped selection exactly:
            # tiling-native (J, g, 128) when (k, n) qualify, else the
            # strided (block, rows) layout — see topk.py
            from byteps_tpu.compression.topk import tiled_shape

            tiled = tiled_shape(self.k, n)
            if tiled is not None:
                J, g = tiled
                x3 = np.abs(xf).reshape(J, g, 128)
                local = np.argmax(x3, axis=1)                 # (J, 128)
                jj = np.arange(J, dtype=np.uint32)[:, None]
                lane = np.arange(128, dtype=np.uint32)[None, :]
                idx = ((jj * np.uint32(g) + local.astype(np.uint32))
                       * np.uint32(128) + lane).reshape(-1)
                k = idx.size
            else:
                rows, block = self._block_shape(n)
                pad = rows * block - n
                xa = np.abs(xf)
                if pad:
                    xa = np.concatenate(
                        [xa, np.full(pad, -1.0, np.float32)])
                local = np.argmax(xa.reshape(block, rows), axis=0)
                idx = (local.astype(np.uint32) * np.uint32(rows)
                       + np.arange(rows, dtype=np.uint32))
                k = rows
        else:
            k = self._k(n)
            idx = np.argpartition(np.abs(xf), n - k)[n - k:].astype(np.uint32)
        out = np.empty(4 + k * 8, np.uint8)
        out[:4] = np.frombuffer(np.uint32(k).tobytes(), np.uint8)
        out[4:4 + k * 4] = idx.view(np.uint8)
        out[4 + k * 4:] = xf[idx].view(np.uint8)
        return out

    def decode(self, buf: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
        buf = np.ascontiguousarray(buf)
        k = int(buf[:4].view(np.uint32)[0])
        idx = buf[4:4 + k * 4].view(np.uint32).astype(np.int64)
        val = buf[4 + k * 4:4 + k * 8].view(np.float32)
        dense = np.zeros(n, np.float32)
        np.add.at(dense, idx, val)
        return dense

    def wire_bytes(self, n: int) -> int:
        if self.selection == "block":
            return 4 + self._block_shape(n)[0] * 8
        return 4 + self._k(n) * 8


class RandomkWire(WireCodec):
    """Values-only wire for seed-synced randomk: every pod derives the same
    k indices from the shared seed, so the server positional-sums k floats
    without ever seeing indices (the reference's synced-PRNG trick); the
    store for this key is k elements, not n."""

    codec_id = WIRE_RAW  # positional fp32 sum on the server

    def __init__(self, k=0.01, scale: bool = True):
        self.k = k
        self.scale = bool(scale)

    def _k(self, n: int) -> int:
        from byteps_tpu.compression.topk import resolve_k

        return resolve_k(self.k, n)

    def _indices(self, n: int, seed: int) -> np.ndarray:
        rng = np.random.Generator(np.random.PCG64(seed))
        return rng.choice(n, size=self._k(n), replace=False)

    def encode(self, x: np.ndarray, seed: int = 0) -> np.ndarray:
        xf = np.ascontiguousarray(x, np.float32)
        n = xf.size
        k = self._k(n)
        vals = xf[self._indices(n, seed)]
        if self.scale:
            vals = vals * np.float32(n / k)
        return vals.astype(np.float32).view(np.uint8).ravel()

    def decode(self, buf: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
        buf = np.ascontiguousarray(buf)
        vals = buf.view(np.float32)
        dense = np.zeros(n, np.float32)
        dense[self._indices(n, seed)] = vals
        return dense

    def store_elems(self, n: int) -> int:
        return self._k(n)

    def wire_bytes(self, n: int) -> int:
        return self._k(n) * 4


class DitherWire(WireCodec):
    """[u8 flags][u8 s][u16 0][f32 norm][n i8 levels] — stochastic
    quantization; flags bit0 = natural (powers-of-two) levels, bit1 =
    max-norm. Level mapping matches DitheringCompressor and codec.cc."""

    codec_id = WIRE_DITHER

    def __init__(self, s: int = 127, partition: str = "linear",
                 normalize: str = "l2"):
        self.s = int(s)
        self.natural = partition == "natural"
        self.maxnorm = normalize == "max"

    @property
    def _flags(self) -> int:
        return (_DITHER_NATURAL if self.natural else 0) | (
            _DITHER_MAXNORM if self.maxnorm else 0
        )

    def encode(self, x: np.ndarray, seed: int = 0) -> np.ndarray:
        xf = np.ascontiguousarray(x, np.float32)
        n = xf.size
        s = self.s
        norm = np.float32(
            np.max(np.abs(xf)) if self.maxnorm
            else np.sqrt(np.sum(xf.astype(np.float64) ** 2))
        ) if n else np.float32(0)
        safe = norm if norm > 0 else np.float32(1)
        p = np.abs(xf) / safe
        u = np.random.Generator(np.random.PCG64(seed)).random(
            n, dtype=np.float32
        )
        if not self.natural:
            y = np.minimum(p, 1.0) * s
            lo = np.floor(y)
            level = lo + (u < (y - lo))
        else:
            tiny = np.float32(2.0 ** (-(s - 1)))
            pc = np.clip(p, tiny, 1.0)
            e = np.floor(np.log2(pc))
            base = np.exp2(e)
            frac = pc / base - 1.0
            q = base * np.where(u < frac, 2.0, 1.0)
            level = np.rint(np.log2(q)) + (s - 1) + 1
            level = np.minimum(level, s)
            below = p < tiny
            level = np.where(
                below, np.where(u < p / tiny, 1.0, 0.0), level
            )
        levels = (np.where(np.signbit(xf), -level, level)).astype(np.int8)
        out = np.empty(8 + n, np.uint8)
        out[0] = self._flags
        out[1] = s
        out[2:4] = 0
        out[4:8] = np.frombuffer(np.float32(norm).tobytes(), np.uint8)
        out[8:] = levels.view(np.uint8)
        return out

    def decode(self, buf: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
        buf = np.ascontiguousarray(buf)
        flags = int(buf[0])
        s = int(buf[1])
        norm = buf[4:8].view(np.float32)[0]
        lv = buf[8:8 + n].view(np.int8).astype(np.float32)
        mag = np.abs(lv)
        if flags & _DITHER_NATURAL:
            p = np.where(mag > 0, np.exp2(mag - 1 - (s - 1)), 0.0)
        else:
            p = mag / s
        return (np.sign(lv) * p * norm).astype(np.float32)

    def wire_bytes(self, n: int) -> int:
        return 8 + n


@dataclasses.dataclass
class WirePlan:
    """How one tensor travels the DCN: push codec + pull codec (two-way
    compression re-compresses the pull direction, reference server
    behavior; one-way pulls raw fp32). For store-compacted codecs
    (randomk), the "raw" pull is already the compact positional sum and is
    decoded by the codec regardless of two_way."""

    codec: WireCodec
    two_way: bool

    @property
    def compacted(self) -> bool:
        # store_elems < n ⇒ the raw store itself is the compressed form
        return type(self.codec).store_elems is not WireCodec.store_elems

    @property
    def pull_codec_id(self) -> int:
        return (
            self.codec.codec_id
            if (self.two_way and not self.compacted)
            else WIRE_RAW
        )

    def pull_capacity(self, n: int) -> int:
        store = self.codec.store_elems(n)
        return max(store * 4, self.codec.wire_bytes(n) if self.two_way else 0)

    def decode_pull(self, buf: np.ndarray, n: int, seed: int) -> np.ndarray:
        if self.compacted or self.two_way:
            return self.codec.decode(buf, n, seed)
        return np.ascontiguousarray(buf[: n * 4]).view(np.float32).copy()


def make_wire_codec(spec: CompressionSpec) -> Optional[WireCodec]:
    """Map a resolved CompressionSpec to its DCN wire codec (None = raw)."""
    c = spec.compressor
    name = c.name
    if name == "identity":
        return None
    if name == "onebit":
        return OnebitWire(scaling=getattr(c, "scaling", True))
    if name == "topk":
        return TopkWire(k=getattr(c, "k", 0.01),
                        selection=getattr(c, "selection", "exact"))
    if name == "randomk":
        return RandomkWire(
            k=getattr(c, "k", 0.01), scale=getattr(c, "scale", True)
        )
    if name == "dithering":
        return DitherWire(
            s=getattr(c, "s", 127),
            partition=getattr(c, "partition", "linear"),
            normalize=getattr(c, "normalize", "l2"),
        )
    if name == "fp16":
        return Fp16Wire()
    if name == "fp8":
        return Fp8Wire()
    raise ValueError(f"no DCN wire codec for compressor '{name}'")
