"""Stochastic (dithered) quantization
(reference: ``byteps/common/compressor/impl/dithering.{h,cc}``).

Quantizes x/||x|| onto s levels with stochastic rounding (unbiased), keeping
the sign; wire format = int8 levels + one fp32 norm. Options mirror the
reference kwargs:

* ``s`` — number of quantization levels (default 127 to fit int8).
* ``partition`` — ``"linear"`` (levels i/s) or ``"natural"`` (powers of two:
  levels 2^-j, denser near zero).
* ``normalize`` — ``"l2"`` or ``"max"`` norm.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor


@register_compressor("dithering")
class DitheringCompressor(Compressor):
    name = "dithering"
    presummable = False  # per-worker norms differ; levels aren't summable
    stochastic = True

    def __init__(
        self,
        s: int = 127,
        partition: str = "linear",
        normalize: str = "l2",
        **_ignored,
    ):
        if partition not in ("linear", "natural"):
            raise ValueError(f"partition must be linear|natural, got {partition}")
        if normalize not in ("l2", "max"):
            raise ValueError(f"normalize must be l2|max, got {normalize}")
        if not 1 <= int(s) <= 127:
            raise ValueError(f"s must be in [1, 127] (levels are stored int8), got {s}")
        self.s = int(s)
        self.partition = partition
        self.normalize = normalize

    def _norm(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.normalize == "l2":
            return jnp.sqrt(jnp.sum(x * x))
        return jnp.max(jnp.abs(x))

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        if rng is None:
            raise ValueError("dithering requires an rng key for stochastic rounding")
        xf = x.astype(jnp.float32)
        norm = self._norm(xf)
        safe = jnp.where(norm > 0, norm, 1.0)
        p = jnp.abs(xf) / safe  # in [0, 1]
        u = jax.random.uniform(rng, xf.shape)
        if self.partition == "linear":
            # scale to [0, s], stochastic-round to integer level
            y = p * self.s
            lo = jnp.floor(y)
            level = lo + (u < (y - lo))
        else:  # natural: levels 0 and 2^j for j in [-(s-1)..0] over p in (0,1]
            # express p = 2^e * m with m in [1,2); round m stochastically to
            # 1 or 2, i.e. quantize onto powers of two
            tiny = jnp.float32(2.0 ** (-(self.s - 1)))
            pc = jnp.clip(p, tiny, 1.0)
            e = jnp.floor(jnp.log2(pc))
            base = jnp.exp2(e)
            frac = pc / base - 1.0  # in [0,1)
            up = (u < frac).astype(jnp.float32)
            q = base * (1.0 + up)  # 2^e or 2^(e+1)
            # kill true zeros / below-tiny values stochastically toward 0
            keep = (u < p / tiny) | (p >= tiny)
            q = jnp.where(keep, q, 0.0)
            # store exponent index as level: j = log2(q) + (s-1), 0 => zero
            level = jnp.where(q > 0, jnp.log2(q) + (self.s - 1) + 1, 0.0)
        sign = jnp.sign(xf)
        levels = (sign * level).astype(jnp.int8)
        return {"levels": levels, "norm": norm.reshape(1)}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        lv = payload["levels"].astype(jnp.float32)
        norm = payload["norm"][0]
        sign = jnp.sign(lv)
        mag = jnp.abs(lv)
        if self.partition == "linear":
            p = mag / self.s
        else:
            p = jnp.where(mag > 0, jnp.exp2(mag - 1 - (self.s - 1)), 0.0)
        return (sign * p * norm).astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        return n + 4
