"""Error-feedback and momentum decorators, as functional pytree state.

Reference analogs:
``byteps/common/compressor/impl/{error_feedback,vanilla_error_feedback}.{h,cc}``
(decorator persisting e ← g' − D(C(g')) with g' = g + e_prev, per partition)
and ``impl/{momentum,nesterov_momentum}.{h,cc}`` (Nesterov momentum applied
*before* compression, because a compressed PS cannot equivalently apply
optimizer-side momentum).

The reference keeps this state in C++ side buffers; under jit it must be
pure, so both decorators are (value, state) → (value, state) functions whose
state the caller (``DistributedOptimizer``) threads through its pytree
(SURVEY §7 hard-parts list).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload


@dataclasses.dataclass
class CompressionSpec:
    """Resolved compression configuration for one tensor/partition."""

    compressor: Compressor
    ef: bool = False
    momentum: bool = False
    mu: float = 0.9
    seed: int = 0
    # compress the pull direction too (reference: server re-compresses the
    # sum before answering pulls). Max wire savings, but the recompression
    # error is NOT covered by worker-side EF — set False for unbiased
    # aggregation of the EF-compensated pushes at 2x pull bandwidth.
    two_way: bool = True

    @property
    def enabled(self) -> bool:
        return self.compressor.name != "identity"


def ef_init_state(n: int, dtype=jnp.float32) -> jnp.ndarray:
    """Initial error-feedback residual (zeros, one per compressed chunk)."""
    return jnp.zeros((n,), dtype)


def momentum_init_state(n: int, dtype=jnp.float32) -> jnp.ndarray:
    return jnp.zeros((n,), dtype)


def momentum_step(
    x: jnp.ndarray, m: jnp.ndarray, mu: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Nesterov momentum pre-compression: m' = μm + x; out = x + μm'."""
    m_new = mu * m + x
    return x + mu * m_new, m_new


def ef_compress(
    compressor: Compressor,
    x: jnp.ndarray,
    e: jnp.ndarray,
    rng: Optional[jnp.ndarray] = None,
) -> Tuple[Payload, jnp.ndarray]:
    """Compress with error feedback.

    corrected = x + e;  payload = C(corrected);
    e' = corrected − D(payload)   (the ``FastUpdateError`` rule).
    """
    corrected = x.astype(jnp.float32) + e
    payload = compressor.compress(corrected, rng)
    approx = compressor.decompress(payload, corrected.shape[0], jnp.float32, rng)
    return payload, corrected - approx
