"""Scaled fp8 (e4m3) compression — half of fp16's wire bytes.

Beyond-reference wire format (the reference stops at fp16,
``byteps/torch/compression.py``): one fp32 absmax scale per partition +
one e4m3 byte per element, quartering raw fp32 push/pull traffic. The
e4m3 grid (4 exponent bits, 3 mantissa, max 448) holds ~2 decimal
digits — with the per-partition scale pinning the dynamic range, the
quantization error is ≤ 2^-4 relative per element, and the error-
feedback decorator (``ef``) recirculates it for convergence-sensitive
runs.

The TPU path quantizes with the native ``jnp.float8_e4m3fn`` dtype
(hardware cast); the DCN wire twin (``wire.Fp8Wire``) uses ml_dtypes on
the host, and the C++ server decodes/re-encodes bit-exactly
(``server/csrc/codec.cc``: ``fp8_to_float`` / ``float_to_fp8``,
round-to-nearest-even — parity asserted over all 256 byte values and
random grids in ``tests/test_dcn.py``).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor

FP8_MAX = 448.0  # largest finite e4m3fn value


@register_compressor("fp8")
class Fp8Compressor(Compressor):
    name = "fp8"
    # per-worker scales differ -> positional byte sums do NOT commute
    presummable = False

    def __init__(self, **_ignored):
        pass

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        xf = x.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf))
        scale = jnp.where(absmax > 0, absmax / FP8_MAX, 1.0)
        q = jnp.clip(xf / scale, -FP8_MAX, FP8_MAX)
        return {"values": q.astype(jnp.float8_e4m3fn), "scale": scale}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        return (payload["values"].astype(jnp.float32)
                * payload["scale"]).astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        return 4 + n
