"""Top-k sparsification (reference: ``byteps/common/compressor/impl/topk.{h,cc}``).

Keeps the k coordinates of largest magnitude; wire format = (index, value)
pairs, matching the reference. ``k`` may be an absolute count or a float
ratio in (0, 1] (interpreted per compressed chunk, as the reference does
per partition).

Three selection strategies (same wire format, same budget, same
densify-sum server path — EF recirculates whatever a near-miss leaves
behind, so all three preserve the sparsifier's contract):

* ``selection="exact"`` (default) — ``lax.top_k``, the reference's
  semantics. On TPU this is catastrophically slow at gradient-chunk
  sizes: a GPT-2-medium fused step measured ~50× slower than the whole
  uncompressed step on one v5e (docs/performance.md).
* ``selection="approx"`` — ``jax.lax.approx_max_k``, the TPU-native
  partial-reduce selection with a ``recall_target`` bound. ~5× faster
  than exact at GPT-2-medium scale, but the dense reconstruction is
  still a scatter (serialized on TPU).
* ``selection="block"`` — blockwise top-1 (local top-k): reshape to
  ``(k, n/k)`` rows, keep each row's argmax. Selection is a pure
  vectorized reduce AND reconstruction is a one-hot multiply — no sort,
  no scatter anywhere, which is why it is the TPU-shaped variant
  (measured ~60× faster end-to-end than exact at GPT-2-medium scale).
  The support differs from global top-k (exactly one winner per block),
  a standard local-selection tradeoff the EF decorator compensates;
  index budget and wire format are identical.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor

_SELECTIONS = ("exact", "approx", "block")


def resolve_k(k: Union[int, float], n: int) -> int:
    if isinstance(k, float) and 0 < k <= 1:
        return max(1, int(n * k))
    return max(1, min(int(k), n))


def block_shape(k: Union[int, float], n: int) -> tuple:
    """(rows, block) with rows*block >= n covering n with ~k winner rows.
    The single source of the block layout — the fused TPU path
    (``TopkCompressor``) and the host wire codec (``TopkWire``) must
    agree on it or their supports/byte counts drift."""
    kk = resolve_k(k, n)
    block = -(-n // kk)         # ceil: block size per winner
    rows = -(-n // block)       # rows actually needed to cover n
    return rows, block


def tiled_shape(k: Union[int, float], n: int):
    """(J, g) for the tiling-native block layout, or None.

    Chunk views as ``(J, g, 128)`` with the last axis the flat array's
    native 128-lane tiling (the reshape is a layout no-op on TPU);
    winner (j, lane) covers ``{(j·g + i)·128 + lane : i < g}`` and
    winners number exactly ``resolve_k``. Shared by the fused path and
    the numpy wire twin — both must pick the same layout for the same
    (k, n) or their supports drift. None → the strided (block, rows)
    fallback layout."""
    kk = resolve_k(k, n)
    if kk % 128 or n % 128 or kk >= n:
        return None
    J, M = kk // 128, n // 128
    if M % J:
        return None
    return J, M // J


@register_compressor("topk")
class TopkCompressor(Compressor):
    name = "topk"
    presummable = False  # per-worker supports differ; must densify to sum

    def __init__(self, k: Union[int, float] = 0.01, approx: bool = False,
                 recall_target: float = 0.95,
                 selection: Optional[str] = None, **_ignored):
        self.k = k
        # approx=True is the compat spelling of selection="approx"
        self.selection = (selection if selection is not None
                          else ("approx" if approx else "exact"))
        if self.selection not in _SELECTIONS:
            raise ValueError(f"unknown selection {self.selection!r} — "
                             f"expected one of {_SELECTIONS}")
        if not 0.0 < recall_target <= 1.0:
            raise ValueError(
                f"recall_target must be in (0, 1]; got {recall_target}")
        self.recall_target = float(recall_target)

    # -- block layout -------------------------------------------------
    def _block_shape(self, n: int) -> tuple:
        return block_shape(self.k, n)

    def _tiled_shape(self, n: int):
        """See :func:`tiled_shape` — the default 4 MB ratio-k partitions
        always qualify; ragged tails and odd absolute-k configs fall
        back to the strided (block, rows) layout."""
        return tiled_shape(self.k, n)

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        n = x.shape[0]
        k = resolve_k(self.k, n)
        xf = x.astype(jnp.float32)
        if self.selection == "block" and k < n:
            tiled = self._tiled_shape(n)
            if tiled is not None:
                # tiling-native fast path: (J, g, 128) view is a layout
                # no-op on the flat chunk (last axis = the native lane
                # tiling), so selection runs with ZERO relayout — the
                # round-5 xprof showed the 2D-reshape relayouts costing
                # ~22 ms/step on GPT-2-medium before this
                J, g = tiled
                x3 = xf.reshape(J, g, 128)
                xa = jnp.abs(x3)
                am = xa.max(axis=1, keepdims=True)             # (J,1,128)
                ii = jax.lax.broadcasted_iota(jnp.int32, (J, g, 128), 1)
                # first-max tie-break == jnp.argmax
                local = jnp.where(xa == am, ii, g).min(axis=1)  # (J,128)
                vals = jnp.where(ii == local[:, None, :], x3,
                                 0.0).sum(axis=1)               # (J,128)
                lane = jnp.arange(128, dtype=jnp.int32)[None, :]
                jj = jnp.arange(J, dtype=jnp.int32)[:, None]
                idx = ((jj * g + local) * 128 + lane)
                return {"indices": idx.reshape(-1),
                        "values": vals.reshape(-1)}
            rows, block = self._block_shape(n)
            pad = rows * block - n
            # STRIDED block layout, (block, rows): winner lanes live on
            # the MINOR axis (rows ≈ k, typically 128-aligned at real
            # partition sizes) and the argmax runs over the short major
            # axis — every op vectorizes at full VPU lane width. The
            # round-4 contiguous layout put `block` (= ceil(n/k), e.g.
            # 100 at 4 MB/k=1%) on the minor axis, misaligning every
            # compare/reduce against the 128-lane registers. Each
            # winner's block is now the strided set {c, c+rows, ...} —
            # same budget, same disjoint-cover semantics, same wire
            # format. Value extraction is compare+where+sum everywhere —
            # not the TPU-hostile x[arange, local] gather the round-5
            # xprof caught as the hottest op of the compressed step.
            if pad == 0:
                # full chunks (the production partition path) run the
                # fused Pallas selection (ops/topk_kernels.py; its jnp
                # twin is the golden and the off-TPU fallback)
                from byteps_tpu.ops.topk_kernels import block_select

                local, vals = block_select(xf.reshape(block, rows))
            else:
                # ragged tail: padding is -1 < 0 <= |x| so a padded slot
                # can never win (every lane has >= 1 real slot: lane c's
                # first member is flat position c < rows <= n)
                xa = jnp.concatenate([jnp.abs(xf), jnp.full((pad,), -1.0)])
                xv = jnp.concatenate([xf, jnp.zeros((pad,))])
                xa = xa.reshape(block, rows)
                local = jnp.argmax(xa, axis=0)                 # (rows,)
                rr = jax.lax.broadcasted_iota(jnp.int32, (block, rows), 0)
                vals = jnp.where(rr == local[None, :],
                                 xv.reshape(block, rows), 0.0).sum(axis=0)
            idx = (local.astype(jnp.int32) * rows
                   + jnp.arange(rows, dtype=jnp.int32))
            return {"indices": idx, "values": vals}
        if self.selection == "approx" and k < n:
            _, idx = jax.lax.approx_max_k(
                jnp.abs(xf), k, recall_target=self.recall_target)
        else:
            # exact; k == n degenerates to identity for every strategy
            _, idx = jax.lax.top_k(jnp.abs(xf), k)
        return {"indices": idx.astype(jnp.int32), "values": xf[idx]}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        idx, vals = payload["indices"], payload["values"]
        tiled = self._tiled_shape(n)
        if (self.selection == "block" and tiled is not None
                and idx.shape[0] == resolve_k(self.k, n)):
            # tiling-native inverse: zero-relayout reconstruction
            J, g = tiled
            local = (idx.reshape(J, 128) // 128
                     - jnp.arange(J, dtype=idx.dtype)[:, None] * g)
            ii = jax.lax.broadcasted_iota(jnp.int32, (J, g, 128), 1)
            dense = jnp.where(
                ii == local[:, None, :],
                vals.reshape(J, 1, 128).astype(jnp.float32), 0.0)
            return dense.reshape(-1).astype(dtype)
        rows, block = self._block_shape(n)
        if self.selection == "block" and idx.shape[0] == rows and block > 1:
            # scatter-free reconstruction on the strided layout: winner
            # lane c holds index local·rows + c, so an iota compare over
            # the (block, rows) grid rebuilds the dense chunk — minor
            # axis aligned, no scatter, no gather; fused Pallas pass on
            # TPU via the K=1 reconstruct-sum kernel
            from byteps_tpu.ops.topk_kernels import block_reconstruct_sum

            local = (idx - jnp.arange(rows, dtype=idx.dtype)) // rows
            dense = block_reconstruct_sum(
                local[None], payload["values"].astype(jnp.float32)[None],
                block).reshape(block * rows)
            return dense[:n].astype(dtype)
        dense = jnp.zeros((n,), jnp.float32)
        dense = dense.at[idx].add(vals)
        return dense.astype(dtype)

    def roundtrip(self, x: jnp.ndarray, rng=None, e=None):
        """Single-worker aggregation body as ONE fused kernel pass when
        the tiled layout applies (ops/topk_kernels.py block_roundtrip):
        EF add + select + reconstruct + new residual with zero payload
        materialization — the round-5 remedy for BASELINE config 4's
        single-chip ratio. Falls back to the generic compose. Winner
        ties break strict first-max (min group index at the group max),
        identical to the payload-producing compress path, so the fused
        n==1 body and the n>1 wire path select the same support."""
        n = x.shape[0]
        tiled = (self._tiled_shape(n)
                 if self.selection == "block" else None)
        if tiled is None:
            return super().roundtrip(x, rng, e)
        from byteps_tpu.ops.topk_kernels import block_roundtrip

        J, g = tiled
        return block_roundtrip(x, J, g, e=e)

    def decompress_sum(self, payloads, n: int, dtype=jnp.float32,
                       rng_keys=None):
        """Fused decompress-then-sum over K stacked payloads — the
        aggregation tier's inner loop (reference server ``SumRecvBuff``)
        as ONE kernel pass on the block layout, no K dense temporaries."""
        idx = payloads["indices"]
        tiled = self._tiled_shape(n)
        if (self.selection == "block" and tiled is not None
                and idx.ndim == 2
                and idx.shape[1] == resolve_k(self.k, n)):
            J, g = tiled
            K = idx.shape[0]
            vals = payloads["values"].astype(jnp.float32)
            ii = jax.lax.broadcasted_iota(jnp.int32, (J, g, 128), 1)
            acc = jnp.zeros((J, g, 128), jnp.float32)
            for ki in range(K):
                local = (idx[ki].reshape(J, 128) // 128
                         - jnp.arange(J, dtype=idx.dtype)[:, None] * g)
                acc = acc + jnp.where(ii == local[:, None, :],
                                      vals[ki].reshape(J, 1, 128), 0.0)
            return acc.reshape(-1).astype(dtype)
        rows, block = self._block_shape(n)
        if (self.selection == "block" and idx.ndim == 2
                and idx.shape[1] == rows and block > 1):
            from byteps_tpu.ops.topk_kernels import block_reconstruct_sum

            lane = jnp.arange(rows, dtype=idx.dtype)[None, :]
            locals_ = (idx - lane) // rows
            dense = block_reconstruct_sum(
                locals_, payloads["values"].astype(jnp.float32),
                block).reshape(block * rows)
            return dense[:n].astype(dtype)
        return super().decompress_sum(payloads, n, dtype, rng_keys)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        if self.selection == "block":
            rows, _ = self._block_shape(n)
            return rows * (4 + itemsize)
        k = resolve_k(self.k, n)
        return k * (4 + itemsize)
