"""Top-k sparsification (reference: ``byteps/common/compressor/impl/topk.{h,cc}``).

Keeps the k coordinates of largest magnitude; wire format = (index, value)
pairs, matching the reference. ``k`` may be an absolute count or a float
ratio in (0, 1] (interpreted per compressed chunk, as the reference does
per partition).

Three selection strategies (same wire format, same budget, same
densify-sum server path — EF recirculates whatever a near-miss leaves
behind, so all three preserve the sparsifier's contract):

* ``selection="exact"`` (default) — ``lax.top_k``, the reference's
  semantics. On TPU this is catastrophically slow at gradient-chunk
  sizes: a GPT-2-medium fused step measured ~50× slower than the whole
  uncompressed step on one v5e (docs/performance.md).
* ``selection="approx"`` — ``jax.lax.approx_max_k``, the TPU-native
  partial-reduce selection with a ``recall_target`` bound. ~5× faster
  than exact at GPT-2-medium scale, but the dense reconstruction is
  still a scatter (serialized on TPU).
* ``selection="block"`` — blockwise top-1 (local top-k): reshape to
  ``(k, n/k)`` rows, keep each row's argmax. Selection is a pure
  vectorized reduce AND reconstruction is a one-hot multiply — no sort,
  no scatter anywhere, which is why it is the TPU-shaped variant
  (measured ~60× faster end-to-end than exact at GPT-2-medium scale).
  The support differs from global top-k (exactly one winner per block),
  a standard local-selection tradeoff the EF decorator compensates;
  index budget and wire format are identical.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor

_SELECTIONS = ("exact", "approx", "block")


def resolve_k(k: Union[int, float], n: int) -> int:
    if isinstance(k, float) and 0 < k <= 1:
        return max(1, int(n * k))
    return max(1, min(int(k), n))


def block_shape(k: Union[int, float], n: int) -> tuple:
    """(rows, block) with rows*block >= n covering n with ~k winner rows.
    The single source of the block layout — the fused TPU path
    (``TopkCompressor``) and the host wire codec (``TopkWire``) must
    agree on it or their supports/byte counts drift."""
    kk = resolve_k(k, n)
    block = -(-n // kk)         # ceil: block size per winner
    rows = -(-n // block)       # rows actually needed to cover n
    return rows, block


@register_compressor("topk")
class TopkCompressor(Compressor):
    name = "topk"
    presummable = False  # per-worker supports differ; must densify to sum

    def __init__(self, k: Union[int, float] = 0.01, approx: bool = False,
                 recall_target: float = 0.95,
                 selection: Optional[str] = None, **_ignored):
        self.k = k
        # approx=True is the compat spelling of selection="approx"
        self.selection = (selection if selection is not None
                          else ("approx" if approx else "exact"))
        if self.selection not in _SELECTIONS:
            raise ValueError(f"unknown selection {self.selection!r} — "
                             f"expected one of {_SELECTIONS}")
        if not 0.0 < recall_target <= 1.0:
            raise ValueError(
                f"recall_target must be in (0, 1]; got {recall_target}")
        self.recall_target = float(recall_target)

    # -- block layout -------------------------------------------------
    def _block_shape(self, n: int) -> tuple:
        return block_shape(self.k, n)

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        n = x.shape[0]
        k = resolve_k(self.k, n)
        xf = x.astype(jnp.float32)
        if self.selection == "block" and k < n:
            rows, block = self._block_shape(n)
            pad = rows * block - n
            xa = jnp.abs(xf)
            if pad:
                # padding is -1 < 0 <= |x|: a padded slot can never win
                # unless the whole row is padding (sliced away below)
                xa = jnp.concatenate([xa, jnp.full((pad,), -1.0)])
                xv = jnp.concatenate([xf, jnp.zeros((pad,))])
            else:
                xv = xf
            xa = xa.reshape(rows, block)
            local = jnp.argmax(xa, axis=1)                     # (rows,)
            idx = (jnp.arange(rows) * block + local).astype(jnp.int32)
            vals = xv.reshape(rows, block)[jnp.arange(rows), local]
            return {"indices": idx, "values": vals}
        if self.selection == "approx" and k < n:
            _, idx = jax.lax.approx_max_k(
                jnp.abs(xf), k, recall_target=self.recall_target)
        else:
            # exact; k == n degenerates to identity for every strategy
            _, idx = jax.lax.top_k(jnp.abs(xf), k)
        return {"indices": idx.astype(jnp.int32), "values": xf[idx]}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        idx, vals = payload["indices"], payload["values"]
        rows, block = self._block_shape(n)
        if self.selection == "block" and idx.shape[0] == rows and block > 1:
            # scatter-free reconstruction: indices follow the per-row
            # pattern (row*block + local), so a one-hot multiply rebuilds
            # the dense chunk — the TPU win over .at[].add
            local = idx - jnp.arange(rows, dtype=idx.dtype) * block
            dense = (jax.nn.one_hot(local, block, dtype=jnp.float32)
                     * vals[:, None]).reshape(rows * block)
            return dense[:n].astype(dtype)
        dense = jnp.zeros((n,), jnp.float32)
        dense = dense.at[idx].add(vals)
        return dense.astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        if self.selection == "block":
            rows, _ = self._block_shape(n)
            return rows * (4 + itemsize)
        k = resolve_k(self.k, n)
        return k * (4 + itemsize)
