"""Top-k sparsification (reference: ``byteps/common/compressor/impl/topk.{h,cc}``).

Keeps the k coordinates of largest magnitude; wire format = (index, value)
pairs, matching the reference. ``k`` may be an absolute count or a float
ratio in (0, 1] (interpreted per compressed chunk, as the reference does
per partition).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor


def resolve_k(k: Union[int, float], n: int) -> int:
    if isinstance(k, float) and 0 < k <= 1:
        return max(1, int(n * k))
    return max(1, min(int(k), n))


@register_compressor("topk")
class TopkCompressor(Compressor):
    name = "topk"
    presummable = False  # per-worker supports differ; must densify to sum

    def __init__(self, k: Union[int, float] = 0.01, **_ignored):
        self.k = k

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        n = x.shape[0]
        k = resolve_k(self.k, n)
        xf = x.astype(jnp.float32)
        _, idx = jax.lax.top_k(jnp.abs(xf), k)
        return {"indices": idx.astype(jnp.int32), "values": xf[idx]}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        dense = jnp.zeros((n,), jnp.float32)
        dense = dense.at[payload["indices"]].add(payload["values"])
        return dense.astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        k = resolve_k(self.k, n)
        return k * (4 + itemsize)
