"""Random-k sparsification (reference: ``byteps/common/compressor/impl/randomk.{h,cc}``).

Keeps k uniformly-sampled coordinates, scaled by n/k for unbiasedness. The
reference synchronizes the PRNG seed across workers so all workers pick the
same indices and the server can sum values positionally without sending
indices; we reproduce that by deriving indices ONLY from the caller-provided
``rng`` key (same key on every worker ⇒ same indices — threefry is
deterministic), so the wire payload is values-only.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor
from byteps_tpu.compression.topk import resolve_k


@register_compressor("randomk")
class RandomkCompressor(Compressor):
    name = "randomk"
    stochastic = True

    def __init__(self, k: Union[int, float] = 0.01, scale: bool = True, **_ignored):
        self.k = k
        self.scale = bool(scale)

    def _indices(self, rng: jnp.ndarray, n: int, k: int) -> jnp.ndarray:
        # without-replacement sample, deterministic in rng
        return jax.random.choice(rng, n, shape=(k,), replace=False).astype(jnp.int32)

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        if rng is None:
            raise ValueError("randomk requires an rng key (synchronized across workers)")
        n = x.shape[0]
        k = resolve_k(self.k, n)
        idx = self._indices(rng, n, k)
        vals = x.astype(jnp.float32)[idx]
        if self.scale:
            vals = vals * (n / k)
        return {"values": vals}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        if rng is None:
            raise ValueError("randomk decompress requires the same rng used to compress")
        k = payload["values"].shape[0]
        idx = self._indices(rng, n, k)
        dense = jnp.zeros((n,), jnp.float32)
        dense = dense.at[idx].add(payload["values"])
        return dense.astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        return resolve_k(self.k, n) * itemsize
