"""IEEE-half compression (reference: the Python-level ``Compression.fp16``
shim in ``byteps/torch/compression.py`` / ``byteps/tensorflow/compression.py``
— there a dtype cast around push_pull; here a first-class registry compressor
so it also rides the DCN wire at half the bytes via ``wire.Fp16Wire``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor


@register_compressor("fp16")
class Fp16Compressor(Compressor):
    name = "fp16"
    presummable = True  # linear codec: positional sums commute with decode

    def __init__(self, **_ignored):
        pass

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        return {"values": x.astype(jnp.float16)}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        return payload["values"].astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        return n * 2
