"""Pluggable gradient compression (reference: ``byteps/common/compressor/``).

Compressors are **pure functions over fixed-shape arrays** so they compose
with jit/vmap/shard_map, unlike the reference's stateful C++ objects; all
carried state (error feedback, momentum) lives in explicit pytrees threaded
through the optimizer (SURVEY §7 "Error-feedback state under jit").

Selection mirrors the reference's ``compression_params`` dict passed to the
framework adapters, e.g.::

    {"compressor": "onebit", "ef": "vanilla", "momentum": "nesterov",
     "scaling": True, "k": 0.01, "seed": 0}
"""

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()

from byteps_tpu.compression.base import (  # noqa: F401
    Compressor,
    from_params,
    get_compressor,
    register_compressor,
)
from byteps_tpu.compression.fp16 import Fp16Compressor  # noqa: F401
from byteps_tpu.compression.fp8 import Fp8Compressor  # noqa: F401
from byteps_tpu.compression.onebit import OnebitCompressor  # noqa: F401
from byteps_tpu.compression.topk import TopkCompressor  # noqa: F401
from byteps_tpu.compression.randomk import RandomkCompressor  # noqa: F401
from byteps_tpu.compression.dithering import DitheringCompressor  # noqa: F401
from byteps_tpu.compression.error_feedback import (  # noqa: F401
    ef_compress,
    ef_init_state,
    momentum_init_state,
    momentum_step,
)
