"""Compressor interface + registry.

Reference analog: ``byteps/common/compressor/compressor.h`` (abstract
``Compressor`` with ``Compress``/``Decompress``/``FastUpdateError``) and
``compressor_registry.cc`` (string-keyed factories instantiated per tensor
from string kwargs).

Contract (all jit/vmap-safe, static shapes):

* ``compress(x, rng=None) -> payload`` — ``x`` is a 1-D array; ``payload``
  is a dict of arrays whose shapes depend only on ``x.shape``/config.
* ``decompress(payload, n, dtype, rng=None) -> x_hat`` — inverse map to a
  dense 1-D array of length ``n``.
* ``compressed_bytes(n, itemsize)`` — wire size, for scheduling/accounting.
* Stochastic compressors take an explicit ``rng`` (threefry key). Compressors
  whose *placement* must agree across workers (randomk) derive it only from
  caller-supplied keys, never from device identity.

The aggregation tier does decompress → fp32 sum → recompress, exactly like
the reference server (``byteps/server/server.cc`` decompress-sum path).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Payload = Dict[str, jnp.ndarray]


class Compressor:
    """Base compressor; identity by default."""

    name = "identity"
    # True if payloads from different workers can be summed positionally
    # without decompressing (all workers emit the same support/encoding —
    # e.g. randomk with synchronized seeds, or identity). The aggregation
    # tier then skips decompress-sum-recompress, like the reference server's
    # positional-sum fast path for seed-synced randomk.
    presummable = True
    # True if compress/decompress REQUIRE an rng key (randomk placement,
    # dithering's stochastic rounding). Callers must then provide a key that
    # advances every step — a constant key silently freezes the sample.
    stochastic = False

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        return {"values": x}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        return payload["values"].astype(dtype)

    def decompress_sum(
        self,
        payloads: Payload,
        n: int,
        dtype=jnp.float32,
        rng_keys: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        """Σ_k decompress(payload_k): the aggregation tier's inner loop
        (reference server: decompress-then-SumRecvBuff per worker push).
        ``payloads`` is the stacked tree (leading axis K); ``rng_keys`` the
        matching (K, ...) keys when the compressor is stochastic. Subclasses
        override with fused kernels; this default just vmaps."""
        import jax

        if rng_keys is None:
            dec = jax.vmap(lambda p: self.decompress(p, n, dtype))(payloads)
        else:
            dec = jax.vmap(
                lambda p, k: self.decompress(p, n, dtype, k)
            )(payloads, rng_keys)
        return dec.sum(axis=0)

    def roundtrip(self, x: jnp.ndarray,
                  rng: Optional[jnp.ndarray] = None,
                  e: Optional[jnp.ndarray] = None):
        """With ``xin = x + e`` (or just ``x``): ``(D(C(xin)),
        xin − D(C(xin)))`` — the single-worker aggregation body
        (reference single-machine mode: compress, "sum" of one,
        decompress) plus the EF add and residual, in one call so
        subclasses can fuse the whole round trip — EF included — into a
        single kernel pass. The default composes the generic methods;
        semantics match the n == 1 collective exactly for deterministic
        codecs (D∘C is idempotent for sign/topk/randomk codecs, so
        skipping the two_way re-compression of an already-compressed
        value changes nothing)."""
        xin = x if e is None else x + e
        dense = self.decompress(self.compress(xin, rng), x.shape[0],
                                jnp.float32, rng)
        return dense, xin - dense

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        return n * itemsize

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{type(self).__name__}>"


_REGISTRY: Dict[str, Callable[..., Compressor]] = {}


def register_compressor(name: str):
    def deco(factory: Callable[..., Compressor]):
        _REGISTRY[name] = factory
        return factory

    return deco


def get_compressor(name: str, **kwargs: Any) -> Compressor:
    if name in (None, "", "identity", "none"):
        return Compressor()
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown compressor '{name}'; registered: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name](**kwargs)


def from_params(params: Optional[Dict[str, Any]]) -> "CompressionSpec":
    """Parse a reference-style ``compression_params`` dict into a spec."""
    from byteps_tpu.compression.error_feedback import CompressionSpec

    params = dict(params or {})
    name = params.pop("compressor", None)
    ef = params.pop("ef", None)
    momentum = params.pop("momentum", None)
    mu = params.pop("mu", 0.9)
    seed = params.pop("seed", 0)
    two_way = params.pop("two_way", True)
    compressor = get_compressor(name, **params) if name else Compressor()
    return CompressionSpec(
        compressor=compressor,
        ef=ef in ("vanilla", True, "1"),
        momentum=momentum in ("nesterov", True, "1"),
        mu=mu,
        seed=seed,
        two_way=bool(two_way),
    )
