"""1-bit sign compression (reference: ``byteps/common/compressor/impl/onebit.{h,cc}``).

Wire format: 32 sign bits packed per uint32 word + one optional fp32 scale.
``scaling=True`` sets scale = mean(|x|) so decompress returns ±mean|x|
(reference kwarg ``scaling`` / env ``BYTEPS_COMPRESSOR_ONEBIT_SCALING``);
otherwise ±1. Compression ratio ≈ 32× vs fp32.

Bit convention: bit=1 ⇔ x >= 0 (non-negative). Padding lanes (beyond n) are
packed as sign of 0 (= 1) and sliced away on decompress.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """bits: (m*32,) of {0,1} int32 -> (m,) uint32."""
    w = bits.reshape(-1, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (w << shifts).sum(axis=1, dtype=jnp.uint32)


def _unpack_bits(words: jnp.ndarray) -> jnp.ndarray:
    """(m,) uint32 -> (m*32,) of {0,1} int32."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[:, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(-1).astype(jnp.int32)


@register_compressor("onebit")
class OnebitCompressor(Compressor):
    name = "onebit"
    presummable = False  # signs cannot be summed; must decompress first

    def __init__(self, scaling: bool = True, **_ignored):
        self.scaling = bool(scaling)

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        n = x.shape[0]
        pad = (-n) % 32
        xf = x.astype(jnp.float32)
        xp = jnp.pad(xf, (0, pad))
        bits = (xp >= 0).astype(jnp.int32)
        words = _pack_bits(bits)
        if self.scaling:
            scale = jnp.mean(jnp.abs(xf)).reshape(1)
        else:
            scale = jnp.ones((1,), jnp.float32)
        return {"signs": words, "scale": scale}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        bits = _unpack_bits(payload["signs"])[:n]
        signs = bits.astype(jnp.float32) * 2.0 - 1.0
        return (signs * payload["scale"][0]).astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        return 4 * ((n + 31) // 32) + 4
