"""1-bit sign compression (reference: ``byteps/common/compressor/impl/onebit.{h,cc}``).

Wire format: sign bits in the TPU-native ``(32, L)`` transposed layout of
``byteps_tpu.ops.onebit_kernels`` (bit k of word j = padded element
``k*L + j``) + one fp32 scale. ``scaling=True`` sets scale = mean(|x|) so
decompress returns ±mean|x| (reference kwarg ``scaling`` / env
``BYTEPS_COMPRESSOR_ONEBIT_SCALING``); otherwise ±1. Compression ratio
≈ 32× vs fp32 for large tensors; the lane padding floors the wire size at
512 bytes + scale per segment, so tiny segments EXPAND — the adapters'
``BYTEPS_MIN_COMPRESS_BYTES`` gate (and honest ``compressed_bytes``
accounting) keeps such tensors uncompressed.

The pack / unpack-and-sum hot ops run as Pallas kernels on TPU (jnp
fallback elsewhere, identical wire layout); the fused
:meth:`decompress_sum` is the aggregation-tier inner loop.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from byteps_tpu.compression.base import Compressor, Payload, register_compressor
from byteps_tpu.ops.onebit_kernels import (
    onebit_pack,
    onebit_unpack,
    onebit_unpack_sum,
    packed_words,
)


@register_compressor("onebit")
class OnebitCompressor(Compressor):
    name = "onebit"
    presummable = False  # signs cannot be summed; must decompress first

    def __init__(self, scaling: Optional[bool] = None, **_ignored):
        if scaling is None:
            # kwarg absent: the reference env var supplies the default
            from byteps_tpu.common.config import _env_bool

            scaling = _env_bool("BYTEPS_COMPRESSOR_ONEBIT_SCALING", True)
        self.scaling = bool(scaling)

    def compress(self, x: jnp.ndarray, rng: Optional[jnp.ndarray] = None) -> Payload:
        xf = x.astype(jnp.float32)
        words = onebit_pack(xf)
        if self.scaling:
            scale = jnp.mean(jnp.abs(xf)).reshape(1)
        else:
            scale = jnp.ones((1,), jnp.float32)
        return {"signs": words, "scale": scale}

    def decompress(
        self,
        payload: Payload,
        n: int,
        dtype=jnp.float32,
        rng: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        return onebit_unpack(payload["signs"], payload["scale"], n).astype(dtype)

    def decompress_sum(
        self,
        payloads: Payload,
        n: int,
        dtype=jnp.float32,
        rng_keys: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        # fused kernel: one VMEM pass over the K payloads
        return onebit_unpack_sum(
            payloads["signs"], payloads["scale"][:, 0], n
        ).astype(dtype)

    def compressed_bytes(self, n: int, itemsize: int = 4) -> int:
        return 4 * packed_words(n) + 4
