"""Build hook: compile the native DCN summation library into the package.

Reference analog: the reference's setup.py builds its C++ core as a CPython
extension. Here the native boundary is a plain shared library driven via
ctypes (no pybind11 in the supported toolchain), so the build step is the
same ``make`` the first-import path uses — wheels ship the .so, editable
installs and source checkouts build lazily on first use
(byteps_tpu/server/native.py).
"""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_native(build_py):
    def run(self):
        csrc = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "byteps_tpu", "server", "csrc",
        )
        if os.path.exists(os.path.join(csrc, "Makefile")):
            # Best-effort: the .so only serves the DCN server tier, and
            # native.py rebuilds it lazily on first use — a missing
            # toolchain must not block installing the JAX/ICI-only paths.
            try:
                subprocess.run(["make", "-C", csrc, "-j4"], check=True)
            except (OSError, subprocess.CalledProcessError) as e:
                print(
                    f"WARNING: native DCN server build skipped ({e}); "
                    "it will be built on first use (requires make + g++)"
                )
        super().run()


setup(cmdclass={"build_py": build_py_with_native})
